"""Fast tests of bench.py's driver-facing behavior (no accelerator, no
model builds): peak-FLOPs resolution and the BENCH_MODE guard. The heavy
measurement paths are exercised on hardware (PERF.md) and by the CPU smoke
invocations documented there."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


class _Dev:
    def __init__(self, kind):
        self.device_kind = kind


def test_peak_tflops_known_chips(monkeypatch):
    monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)
    assert bench._peak_tflops([_Dev("TPU v5 lite")]) == 197.0
    assert bench._peak_tflops([_Dev("TPU v5e")]) == 197.0
    assert bench._peak_tflops([_Dev("TPU v5p")]) == 459.0
    assert bench._peak_tflops([_Dev("TPU v4")]) == 275.0


def test_peak_tflops_unknown_is_zero_no_bogus_mfu(monkeypatch):
    """Unrecognized devices (e.g. the CPU fallback) must not get a made-up
    peak — a 0.0 peak makes child_jax omit the MFU row entirely."""
    monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)
    assert bench._peak_tflops([_Dev("cpu")]) == 0.0


def test_peak_tflops_env_override(monkeypatch):
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "123.5")
    assert bench._peak_tflops([_Dev("cpu")]) == 123.5


def test_empty_bench_mode_means_attack_default(monkeypatch, capsys):
    """BENCH_MODE= (empty) follows the codebase's empty-string-means-unset
    convention: main() proceeds with the attack benchmark (here: children
    stubbed out, so it reaches the could-not-run path) instead of emitting
    the unknown-mode error."""
    monkeypatch.setenv("BENCH_MODE", "")
    monkeypatch.setattr(bench, "run_child",
                        lambda *a, **k: (None, "timeout", ""))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "benchmark could not run"  # not the mode error


@pytest.mark.parametrize("mode", ["bogus", "CERTIFY", " attack"])
def test_unknown_bench_mode_yields_error_json(mode):
    """The orchestrator rejects unknown BENCH_MODE before spawning any
    (expensive, device-claiming) children — main() returns the error line
    immediately, so this subprocess finishes in milliseconds."""
    env = dict(os.environ)
    env["BENCH_MODE"] = mode
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(bench.__file__), "bench.py")],
        env=env, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "error" in rec and mode in rec["error"]
    assert rec["value"] == 0.0


def test_unknown_bench_remat_policy_yields_error_json(monkeypatch, capsys):
    """BENCH_REMAT_POLICY is validated at orchestrator entry; empty means
    the full-remat default."""
    for var in ("BENCH_MODE", "BENCH_GN", "BENCH_EOT", "BENCH_IMG",
                "BENCH_ARCH"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_REMAT_POLICY", "convs")
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "BENCH_REMAT_POLICY" in rec["error"] and rec["value"] == 0.0

    monkeypatch.setenv("BENCH_REMAT_POLICY", "")
    monkeypatch.setattr(bench, "run_child",
                        lambda *a, **k: (None, "timeout", ""))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "benchmark could not run"


def test_unknown_bench_gn_yields_error_json(monkeypatch, capsys):
    """BENCH_GN is validated at orchestrator entry (same convention as
    BENCH_MODE) instead of failing deep inside the jax child at first
    model trace; empty means auto."""
    for var in ("BENCH_MODE", "BENCH_EOT", "BENCH_IMG", "BENCH_ARCH"):
        monkeypatch.delenv(var, raising=False)  # hermetic vs ambient BENCH_*
    monkeypatch.setenv("BENCH_GN", "fused")
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "BENCH_GN" in rec["error"] and rec["value"] == 0.0

    monkeypatch.setenv("BENCH_GN", "")
    monkeypatch.setattr(bench, "run_child",
                        lambda *a, **k: (None, "timeout", ""))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "benchmark could not run"  # not the GN error


def test_gn_crash_retries_flax_and_tags_row(monkeypatch, capsys):
    """A BENCH_GN=auto attack child crashing with a Mosaic/Pallas signature
    in its stderr tail triggers exactly one retry with the flax GN; the
    successful row is tagged gn_fallback. A timeout (wedged accelerator)
    must NOT trigger the retry (see the could-not-run tests)."""
    for var in ("BENCH_MODE", "BENCH_GN", "BENCH_REMAT_POLICY", "BENCH_EOT",
                "BENCH_IMG", "BENCH_ARCH", "BENCH_TOTAL_BUDGET"):
        monkeypatch.delenv(var, raising=False)
    calls = []

    def stub(role, timeout_s, env_extra):
        calls.append((role, dict(env_extra)))
        if role == "torch":
            return {"ips": 1.0}, None, ""
        if env_extra.get("BENCH_GN") == "flax":
            return {"ips": 50.0, "batch": 8}, None, ""
        return None, "crash", "INTERNAL: Mosaic failed to compile kernel"

    monkeypatch.setattr(bench, "run_child", stub)
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["gn_fallback"] == "flax"
    assert rec["value"] == 50.0 and rec["vs_baseline"] == 50.0
    jax_calls = [c for c in calls if c[0] == "jax"]
    assert len(jax_calls) == 2 and jax_calls[1][1]["BENCH_GN"] == "flax"


def test_fallback_cause_names_the_last_failure(monkeypatch, capsys):
    """Kernel crash -> flax retry -> retry TIMES OUT: the fallback row's
    cause must be the retry's timeout, not the first child's kernel crash
    (a reader would otherwise chase a kernel regression when the
    accelerator was simply wedged)."""
    for var in ("BENCH_MODE", "BENCH_GN", "BENCH_REMAT_POLICY", "BENCH_EOT",
                "BENCH_IMG", "BENCH_ARCH", "BENCH_TOTAL_BUDGET"):
        monkeypatch.delenv(var, raising=False)

    def stub(role, timeout_s, env_extra):
        if role == "torch":
            return {"ips": 1.0}, None, ""
        if env_extra.get("JAX_PLATFORMS") == "cpu":
            return {"ips": 3.0, "batch": 2}, None, ""
        if env_extra.get("BENCH_GN") == "flax":
            return None, "timeout", ""
        return None, "crash", "INTERNAL: Mosaic failed to compile kernel"

    monkeypatch.setattr(bench, "run_child", stub)
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["fallback"] == "cpu"
    assert rec["fallback_cause"] == "timeout"


# --------------------------------------------- r04: outage-proofing (VERDICT
# round-3 weak #1: a dead-tunnel child was classified as a kernel crash and
# the flax retry burned the driver's whole budget before the CPU fallback)


def test_classify_failure():
    assert bench.classify_failure("timeout", "anything") == "timeout"
    assert bench.classify_failure(
        "crash", "jaxlib...: UNAVAILABLE: failed to connect to all "
        "addresses") == "backend-init"
    assert bench.classify_failure(
        "crash", "RuntimeError: Unable to initialize backend 'axon'"
    ) == "backend-init"
    assert bench.classify_failure(
        "crash", "INTERNAL: Mosaic lowering failed") == "kernel"
    assert bench.classify_failure(
        "crash", "pallas_call: ... exceeds available VMEM") == "kernel"
    assert bench.classify_failure(
        "crash", "FileNotFoundError: no dataset") == "other"
    # an HBM OOM is NOT a kernel failure: the flax-GN retry would meet the
    # same fate, so it must go straight to the CPU fallback
    assert bench.classify_failure(
        "crash", "RESOURCE_EXHAUSTED: Ran out of memory in memory space hbm"
    ) == "other"
    assert bench.classify_failure(
        "crash", "Mosaic: exceeded VMEM in memory space vmem") == "kernel"
    assert bench.classify_failure("no-json", "") == "other"
    # a budget-skipped child was never attempted: don't misattribute it as
    # an unrelated crash in the row's fallback_cause
    assert bench.classify_failure("budget", "") == "budget"


def test_backend_unavailable_skips_retry_goes_to_cpu(monkeypatch, capsys):
    """The r03 outage transcript, replayed: the jax child dies fast with an
    UNAVAILABLE tail. The orchestrator must NOT re-try the accelerator with
    flax GN (useless against a dead backend) — the very next jax child must
    be the CPU fallback, and the row must carry fallback=cpu."""
    for var in ("BENCH_MODE", "BENCH_GN", "BENCH_REMAT_POLICY", "BENCH_EOT",
                "BENCH_IMG", "BENCH_ARCH", "BENCH_TOTAL_BUDGET"):
        monkeypatch.delenv(var, raising=False)
    calls = []

    def stub(role, timeout_s, env_extra):
        calls.append((role, dict(env_extra)))
        if role == "torch":
            return {"ips": 0.5}, None, ""
        if env_extra.get("JAX_PLATFORMS") == "cpu":  # the CPU fallback
            return {"ips": 4.0, "batch": 2}, None, ""
        return None, "crash", ("E0000 ... UNAVAILABLE: failed to connect\n"
                               "RuntimeError: Unable to initialize backend "
                               "'axon'")

    monkeypatch.setattr(bench, "run_child", stub)
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["fallback"] == "cpu" and rec["value"] == 4.0
    # r05 (VERDICT r04 weak #1): a fallback row must be self-describingly
    # non-comparable, label the config the child ACTUALLY ran (EOT=8, not
    # the ambient default 128), and name its cause + baseline config
    assert rec["comparable"] is False
    assert rec["fallback_cause"] == "backend-init"
    assert "not a TPU measurement" in rec["note"]
    assert "EOT=8" in rec["metric"] and "resnet18@32" in rec["metric"]
    assert rec["baseline"] == {"impl": "torch-cpu-fp32", "arch": "resnet18",
                               "img": 32, "mode": "attack"}
    jax_calls = [c for c in calls if c[0] == "jax"]
    # exactly one accelerator generation + one CPU generation, no flax retry
    assert len(jax_calls) == 2
    assert "BENCH_GN" not in jax_calls[1][1]
    assert jax_calls[1][1]["JAX_PLATFORMS"] == "cpu"


def test_deadline_slices_and_reserves():
    t = [0.0]
    d = bench._Deadline(1000, clock=lambda: t[0])
    assert d.slice(1800, 660) == 340  # clipped by budget - reserve
    assert d.slice(300, 660) == 300   # own timeout smaller than the slice
    t[0] = 990.0
    assert d.slice(1800, 0) == 10
    assert d.slice(1800, 660) == 0    # nothing left after the reserve
    t[0] = 2000.0
    assert d.remaining() == 0.0


def test_total_budget_clips_child_timeouts(monkeypatch, capsys):
    """With BENCH_TOTAL_BUDGET set, no child may be spawned with a timeout
    that could push the orchestrator past the budget: the first child's
    slice is budget minus the fallback+torch reserves."""
    for var in ("BENCH_MODE", "BENCH_GN", "BENCH_REMAT_POLICY", "BENCH_EOT",
                "BENCH_IMG", "BENCH_ARCH"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "1000")
    seen = []

    def stub(role, timeout_s, env_extra):
        seen.append((role, timeout_s))
        if role == "torch":
            return {"ips": 1.0}, None, ""
        return {"ips": 10.0, "batch": 8}, None, ""

    monkeypatch.setattr(bench, "run_child", stub)
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 10.0
    assert seen[0][0] == "jax" and seen[0][1] <= 1000 - 660
    assert seen[1][0] == "torch" and seen[1][1] <= 600


def test_exhausted_budget_still_prints_json(monkeypatch, capsys):
    """Even a budget too small to spawn ANY child must yield the error JSON
    line immediately — the driver always gets its row (r03's rc=124 was
    exactly this guarantee failing)."""
    for var in ("BENCH_MODE", "BENCH_GN", "BENCH_REMAT_POLICY", "BENCH_EOT",
                "BENCH_IMG", "BENCH_ARCH"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "5")
    spawned = []
    monkeypatch.setattr(
        bench, "run_child",
        lambda *a, **k: spawned.append(a) or (None, "timeout", ""))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "benchmark could not run" and rec["value"] == 0.0
    assert not spawned  # nothing was allowed to claim the (dead) device


def test_signal_death_is_kernel_suspect():
    """A miscompiled kernel dies by SIGSEGV/SIGABRT with no traceback:
    run_child appends a signal marker and classify_failure treats it as
    kernel-suspect (one flax retry). SIGKILL (host OOM-killer) is NOT."""
    assert bench.classify_failure(
        "crash", "...\n[child terminated by signal 11]") == "kernel"
    assert bench.classify_failure(
        "crash", "[child terminated by signal 6]") == "kernel"
    assert bench.classify_failure(
        "crash", "[child terminated by signal 9]") == "other"
