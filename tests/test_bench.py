"""Fast tests of bench.py's driver-facing behavior (no accelerator, no
model builds): peak-FLOPs resolution and the BENCH_MODE guard. The heavy
measurement paths are exercised on hardware (PERF.md) and by the CPU smoke
invocations documented there."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


class _Dev:
    def __init__(self, kind):
        self.device_kind = kind


def test_peak_tflops_known_chips(monkeypatch):
    monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)
    assert bench._peak_tflops([_Dev("TPU v5 lite")]) == 197.0
    assert bench._peak_tflops([_Dev("TPU v5e")]) == 197.0
    assert bench._peak_tflops([_Dev("TPU v5p")]) == 459.0
    assert bench._peak_tflops([_Dev("TPU v4")]) == 275.0


def test_peak_tflops_unknown_is_zero_no_bogus_mfu(monkeypatch):
    """Unrecognized devices (e.g. the CPU fallback) must not get a made-up
    peak — a 0.0 peak makes child_jax omit the MFU row entirely."""
    monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)
    assert bench._peak_tflops([_Dev("cpu")]) == 0.0


def test_peak_tflops_env_override(monkeypatch):
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "123.5")
    assert bench._peak_tflops([_Dev("cpu")]) == 123.5


def test_empty_bench_mode_means_attack_default(monkeypatch, capsys):
    """BENCH_MODE= (empty) follows the codebase's empty-string-means-unset
    convention: main() proceeds with the attack benchmark (here: children
    stubbed out, so it reaches the could-not-run path) instead of emitting
    the unknown-mode error."""
    monkeypatch.setenv("BENCH_MODE", "")
    monkeypatch.setattr(bench, "run_child",
                        lambda *a, **k: (None, "timeout"))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "benchmark could not run"  # not the mode error


@pytest.mark.parametrize("mode", ["bogus", "CERTIFY", " attack"])
def test_unknown_bench_mode_yields_error_json(mode):
    """The orchestrator rejects unknown BENCH_MODE before spawning any
    (expensive, device-claiming) children — main() returns the error line
    immediately, so this subprocess finishes in milliseconds."""
    env = dict(os.environ)
    env["BENCH_MODE"] = mode
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(bench.__file__), "bench.py")],
        env=env, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "error" in rec and mode in rec["error"]
    assert rec["value"] == 0.0


def test_unknown_bench_remat_policy_yields_error_json(monkeypatch, capsys):
    """BENCH_REMAT_POLICY is validated at orchestrator entry; empty means
    the full-remat default."""
    for var in ("BENCH_MODE", "BENCH_GN", "BENCH_EOT", "BENCH_IMG",
                "BENCH_ARCH"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_REMAT_POLICY", "convs")
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "BENCH_REMAT_POLICY" in rec["error"] and rec["value"] == 0.0

    monkeypatch.setenv("BENCH_REMAT_POLICY", "")
    monkeypatch.setattr(bench, "run_child",
                        lambda *a, **k: (None, "timeout"))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "benchmark could not run"


def test_unknown_bench_gn_yields_error_json(monkeypatch, capsys):
    """BENCH_GN is validated at orchestrator entry (same convention as
    BENCH_MODE) instead of failing deep inside the jax child at first
    model trace; empty means auto."""
    for var in ("BENCH_MODE", "BENCH_EOT", "BENCH_IMG", "BENCH_ARCH"):
        monkeypatch.delenv(var, raising=False)  # hermetic vs ambient BENCH_*
    monkeypatch.setenv("BENCH_GN", "fused")
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "BENCH_GN" in rec["error"] and rec["value"] == 0.0

    monkeypatch.setenv("BENCH_GN", "")
    monkeypatch.setattr(bench, "run_child",
                        lambda *a, **k: (None, "timeout"))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "benchmark could not run"  # not the GN error


def test_gn_crash_retries_flax_and_tags_row(monkeypatch, capsys):
    """A crashed BENCH_GN=auto attack child triggers exactly one retry with
    the flax GN; the successful row is tagged gn_fallback. A timeout (wedged
    accelerator) must NOT trigger the retry (see the could-not-run tests)."""
    for var in ("BENCH_MODE", "BENCH_GN", "BENCH_REMAT_POLICY", "BENCH_EOT",
                "BENCH_IMG", "BENCH_ARCH"):
        monkeypatch.delenv(var, raising=False)
    calls = []

    def stub(role, timeout_s, env_extra):
        calls.append((role, dict(env_extra)))
        if role == "torch":
            return {"ips": 1.0}, None
        if env_extra.get("BENCH_GN") == "flax":
            return {"ips": 50.0, "batch": 8}, None
        return None, "crash"

    monkeypatch.setattr(bench, "run_child", stub)
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["gn_fallback"] == "flax"
    assert rec["value"] == 50.0 and rec["vs_baseline"] == 50.0
    jax_calls = [c for c in calls if c[0] == "jax"]
    assert len(jax_calls) == 2 and jax_calls[1][1]["BENCH_GN"] == "flax"
