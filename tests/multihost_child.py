"""Child process for the 2-process CPU multi-host test (BASELINE config 5).

Launched twice by `test_parallel.py::test_two_process_multihost_feeding`
with `jax.distributed` over a localhost coordinator; each process owns 4
virtual CPU devices of a global 8-device `(data=2, mask=4)` mesh and feeds
ONLY its local shard of the batch through
`parallel.place_batch_multihost` — the TPU-native analog of per-host data
loading on a multi-host pod. Asserts:

  1. the global array assembles with the right shape/sharding and values
     (per-process constant shards -> distinguishable global sums);
  2. one jitted sharded DorPatch attack block runs to completion over the
     multi-process mesh and returns finite metrics on every process.

Usage: multihost_child.py <process_id> <coordinator_port>
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dorpatch_tpu import losses, parallel  # noqa: E402
from dorpatch_tpu import masks as masks_lib  # noqa: E402
from dorpatch_tpu.config import AttackConfig  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4
mesh = parallel.make_mesh(2, 4)

# ---- 1. multihost feeding: per-process shards -> one global batch ----
local = np.full((2, 8, 8, 3), float(pid), np.float32)
local_y = np.full((2,), pid, np.int32)
x, y = parallel.place_batch_multihost(mesh, local, local_y)
assert x.shape == (4, 8, 8, 3), x.shape
assert y.shape == (4,)
sums = jax.jit(lambda a: a.sum(axis=(1, 2, 3)))(x)
got = np.sort(np.asarray(multihost_utils.process_allgather(sums, tiled=True)))
np.testing.assert_allclose(got, [0.0, 0.0, 192.0, 192.0])

# ---- 2. a sharded attack block over the multi-process mesh ----


def toy_apply(params, xx):
    s = xx.mean(axis=(1, 2))
    return jnp.stack([s[:, 0], s[:, 1], s[:, 2], s.sum(-1) / 3.0], -1) * 10


cfg = AttackConfig(sampling_size=4, dropout=1, dropout_sizes=(0.06,),
                   basic_unit=4, max_iterations=2, sweep_interval=2,
                   switch_iteration=2)
attack = parallel.make_sharded_attack(toy_apply, None, 4, cfg, mesh,
                                      remat=False)
universe = jnp.asarray(masks_lib.dropout_universe(8, 1, (0.06,)))
lv = jnp.mean(losses.local_variance(x)[0], axis=-1)
state = attack._init_state(jax.random.PRNGKey(0), x, y, False,
                           universe.shape[0])
state = attack._get_block(1, 8, 2)(state, x, lv, universe)
metrics = np.asarray(state.metrics)  # replicated -> addressable everywhere
assert np.isfinite(metrics).all(), metrics
assert int(np.asarray(state.step)) == 2
print(f"proc {pid}: OK (metrics[0]={metrics[0]:.4f})", flush=True)
