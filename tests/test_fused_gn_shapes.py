"""Shape coverage for the fused GN kernel: every (HW, C) slab the RN50-BiT
victim will hand the kernel on TPU, plus the VMEM gate boundary.

Run in the kernel's jnp twin (identical math, fast on CPU) for the full
sweep and interpret mode for a representative large/small pair — so an
on-chip Mosaic compile of the victim encounters no slab geometry this suite
has not pinned.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dorpatch_tpu.ops import fused_gn

# (H, W, C) of every distinct GroupNormRelu input in ResNetV2-50x1 @224
# (stem 64ch at 56x56 after pool; per-stage norm1/norm2/norm3 shapes; final
# norm at 7x7x2048). Derived from models/resnetv2.py layer arithmetic.
RN50_GN_SHAPES = sorted({
    (56, 56, 64), (56, 56, 256),
    (28, 28, 128), (56, 56, 128), (28, 28, 512),
    (14, 14, 256), (28, 28, 256), (14, 14, 1024),
    (7, 7, 512), (14, 14, 512), (7, 7, 2048),
})


def _flax(x, scale, bias):
    import flax.linen as nn

    y = nn.GroupNorm(num_groups=32, epsilon=1e-5, dtype=jnp.float32).apply(
        {"params": {"scale": scale, "bias": bias}}, x)
    return nn.relu(y).astype(x.dtype)


@pytest.mark.parametrize("shape", RN50_GN_SHAPES)
def test_all_rn50_slabs_jnp(shape):
    h, w, c = shape
    k = jax.random.PRNGKey(hash(shape) % (2**31))
    x = jax.random.normal(k, (2, h, w, c), jnp.float32).astype(jnp.bfloat16)
    scale = jnp.linspace(0.5, 1.5, c)
    bias = jnp.linspace(-0.2, 0.2, c)
    got = fused_gn.gn_relu(x, scale, bias, 32, impl="jnp")
    want = _flax(x, scale, bias)
    # bf16 outputs: reduction-order differences cost up to ~2 ulps, which
    # scales with magnitude — combined rel+abs tolerance
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=0.016, atol=0.02)
    # every RN50 slab is admissible at bf16 (the attack's compute dtype):
    # forward fits whole-slab, backward has a feasible plan — the largest
    # slab (56x56x256) via the 2-tile HW-tiled backward, the rest untiled
    assert (fused_gn._fwd_vmem_bytes(h * w * c, 2)
            <= fused_gn._VMEM_BUDGET_BYTES)
    plan = fused_gn._bwd_plan(h * w, c, 2)
    assert plan == (2 if shape == (56, 56, 256) else 1)


@pytest.mark.parametrize("shape", [(56, 56, 256), (7, 7, 2048)])
def test_extreme_slabs_interpret(shape):
    """Largest and most-channels slabs through the actual kernel
    (interpreter): the exact grid/block geometry Mosaic will lower."""
    h, w, c = shape
    x = jax.random.normal(jax.random.PRNGKey(0), (2, h, w, c), jnp.float32)
    scale = jnp.ones((c,))
    bias = jnp.zeros((c,))
    got = fused_gn.gn_relu(x, scale, bias, 32, impl="interpret")
    want = _flax(x, scale, bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)
