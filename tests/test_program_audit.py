"""The jaxpr-level program auditor (analysis/entrypoints.py + program.py):
per-rule positive/negative fixture programs, the entry-point registry round
trip (every timed_first_call site discoverable and auditable), the shipped
tree staying clean, allowlist/noqa suppression semantics, and the CLI
`--trace` exit-code contract."""

import pathlib
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import observe
from dorpatch_tpu.analysis import entrypoints as ep_mod
from dorpatch_tpu.analysis import program
from dorpatch_tpu.analysis.cli import main as cli_main
from dorpatch_tpu.analysis.entrypoints import (
    EntryPoint,
    abstractify,
    capture_entrypoints,
    clear_entrypoints,
    production_entrypoints,
    register_entrypoint,
    registered_entrypoints,
    uncovered_names,
)

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "analysis"
sys.path.insert(0, str(FIXTURES))

import trace_programs  # noqa: E402  (fixture module, see path insert)


def rule_ids(findings):
    return sorted({f.rule_id for f in findings})


# ---------- per-rule positives / negatives ----------

@pytest.mark.parametrize("rule_id", sorted(trace_programs.PER_RULE))
def test_trace_rule_positive_fires(rule_id):
    pos, _ = trace_programs.PER_RULE[rule_id]
    findings = program.audit_entrypoint(pos())
    assert rule_id in rule_ids(findings), \
        f"{rule_id} did not fire: {[f.render() for f in findings]}"


@pytest.mark.parametrize("rule_id", sorted(trace_programs.PER_RULE))
def test_trace_rule_negative_clean(rule_id):
    _, neg = trace_programs.PER_RULE[rule_id]
    if neg is None:
        pytest.skip("no clean twin")
    findings = program.audit_entrypoint(neg())
    assert rule_id not in rule_ids(findings), \
        f"false positive: {[f.render() for f in findings]}"


def test_dp201_scan_carry_flagged_without_execution():
    """Acceptance: an unstable scan carry is DP201 — and the program is
    never executed (the trace itself fails, so it cannot be)."""
    findings = program.audit_entrypoint(trace_programs.scan_carry())
    assert rule_ids(findings) == ["DP201"]
    assert "failed to trace" in findings[0].message


def test_dp201_weak_carry_regression():
    """The PR 2 seed bug class (weak-typed `jnp.full` carry init) is now a
    pre-run finding, not a runtime watchdog trip."""
    (f,) = program.audit_entrypoint(trace_programs.weak_carry())
    assert f.rule_id == "DP201"
    assert "weak" in f.message


def test_dp205_unbound_axis_flagged_and_bound_clean():
    """Acceptance: a shard_map body psum over an unbound axis is DP205;
    the properly bound twin is clean on the 8-device CPU mesh."""
    findings = program.audit_entrypoint(trace_programs.unbound_axis())
    assert rule_ids(findings) == ["DP205"]
    assert not program.audit_entrypoint(trace_programs.bound_axis())


def test_dp205_jaxpr_walk_catches_ambient_axis():
    """The jaxpr-walk side of DP205 (not just the trace-error mapping): a
    program traced under an AMBIENT axis env (`make_jaxpr(axis_env=...)`)
    carries a psum with no binder inside the jaxpr at all — exactly the
    fragment shape that deadlocks when compiled standalone."""
    jxp = jax.make_jaxpr(lambda x: jax.lax.psum(x, "i"),
                         axis_env=[("i", 2)])(jnp.zeros((4,)))
    ctx = program.ProgramContext(
        name="fx.walk", fn=None, jaxpr=jxp, args=(), out_avals_tree=None,
        args_info=None, path="<fx>", line=1)
    findings = list(program._TRACE_REGISTRY["DP205"].check(ctx))
    assert findings and findings[0].rule_id == "DP205"
    assert "'i'" in findings[0].message


def test_dp202_f64_leak_flagged():
    with jax.experimental.enable_x64():
        @jax.jit
        def program_f64(x):
            return x.astype(jnp.float64).sum()

        ep = EntryPoint(name="fx.f64", fn=program_f64,
                        args=(jax.ShapeDtypeStruct((4,), jnp.float32),))
        findings = program.audit_entrypoint(ep)
    assert "DP202" in rule_ids(findings)
    assert any("float64" in f.message for f in findings)


def test_dp204_attack_style_vjp_residue_stays_quiet():
    """value_and_grad leaves cheap dead primal equations in every real
    program; DP204 must only fire on dead REAL compute."""

    @jax.jit
    def step(w, x):
        def loss(w):
            return jnp.tanh(x @ w).sum()

        return jax.value_and_grad(loss)(w)

    ep = EntryPoint(name="fx.vjp", fn=step,
                    args=(abstractify(jnp.zeros((4, 4))),
                          abstractify(jnp.zeros((2, 4)))))
    assert "DP204" not in rule_ids(program.audit_entrypoint(ep))


# ---------- suppression: allowlist + source noqa ----------

def test_allowlist_glob_suppresses():
    assert program.allowed("model.init.cifar_vit", "DP204")
    assert not program.allowed("model.init.cifar_vit", "DP203")
    findings = program.audit_entrypoint(
        trace_programs.dead_matmul(),
        allow={"fx.dead_*": {"DP204": "fixture"}})
    assert "DP204" not in rule_ids(findings)


def test_noqa_on_def_line_suppresses(tmp_path):
    mod = tmp_path / "noqa_prog.py"
    mod.write_text(textwrap.dedent("""\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def weak_out(x):  # noqa: DP202 — fixture: weak output is the point
            return jnp.full((2,), 3.0)
    """), encoding="utf-8")
    sys.path.insert(0, str(tmp_path))
    try:
        import noqa_prog
        ep = EntryPoint(name="fx.noqa", fn=noqa_prog.weak_out,
                        args=(abstractify(jnp.zeros((4,))),))
        assert not program.audit_entrypoint(ep)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("noqa_prog", None)


# ---------- registry round trip ----------

def test_capture_records_wraps_and_calls():
    clear_entrypoints()
    try:
        with capture_entrypoints():
            fn = observe.timed_first_call(
                jax.jit(lambda x: x * 2.0), "fx.captured")
            assert uncovered_names() == ["fx.captured"]  # wrap discovered
            fn(jnp.ones((3,), jnp.float32))              # call attaches args
        (ep,) = registered_entrypoints()
        assert ep.name == "fx.captured" and ep.source == "captured"
        assert isinstance(ep.args[0], jax.ShapeDtypeStruct)
        assert uncovered_names() == []
    finally:
        clear_entrypoints()


def test_uncovered_wrap_is_dp200():
    findings = program.audit_entrypoints([], uncovered=["fx.orphan"])
    assert rule_ids(findings) == ["DP200"]
    assert "fx.orphan" in findings[0].message


def test_register_entrypoint_uses_wrapper_name():
    clear_entrypoints()
    try:
        wrapped = observe.timed_first_call(jax.jit(lambda x: x + 1),
                                           "fx.named")
        ep = register_entrypoint(wrapped, (jnp.zeros((2,)),))
        assert ep.name == "fx.named"
        # the timer wrapper is stripped; the jit object (with its static
        # arg/donation metadata) survives
        assert hasattr(ep.fn, "trace")
    finally:
        clear_entrypoints()


def test_production_registry_round_trip():
    """Every timed_first_call site the production stack constructs is
    discoverable AND auditable: enumeration leaves nothing uncovered, and
    the expected program families are all present."""
    eps = production_entrypoints()
    names = {e.name for e in eps}
    expected = {
        "attack.block.stage0.steps50", "attack.block.stage1.steps50",
        "attack.sweep", "train.init", "train.step", "train.eval_step",
        "model.init.cifar_resnet18", "serve.clean_predict[b1]",
        "serve.clean_predict[b4]", "ops.masked_fill.sharded_grad",
    }
    assert expected <= names, f"missing: {expected - names}"
    assert any(n.startswith("defense.predict.r") for n in names)
    assert uncovered_names() == []


def test_attack_init_state_strong_typed():
    """Trace-level pin of the PR 2 fix: no leaf of the attack carry init
    is weak-typed (a regression re-traces every block program)."""
    from dorpatch_tpu.attack import DorPatch
    from dorpatch_tpu.config import AttackConfig

    atk = DorPatch(lambda p, x: x.mean(axis=(1, 2)), None, 3,
                   AttackConfig(sampling_size=4, dropout=1))
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    x = jax.ShapeDtypeStruct((2, 32, 32, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((2,), jnp.int32)
    state = jax.eval_shape(
        lambda k, xx, yy: atk._init_state(k, xx, yy, False, 16), key, x, y)
    weak = [jax.tree_util.keystr(kp)
            for kp, leaf in jax.tree_util.tree_flatten_with_path(state)[0]
            if getattr(leaf, "weak_type", False)]
    assert not weak, f"weak-typed carry init leaves: {weak}"


# ---------- the shipped tree stays clean ----------

def test_shipped_tree_trace_clean():
    findings = program.audit_production()
    assert not findings, "\n".join(f.render() for f in findings)


# ---------- CLI ----------

def test_cli_trace_exit_codes(capsys):
    rc = cli_main(["--trace", "--entrypoints",
                   "trace_programs:clean_entrypoints"])
    assert rc == 0
    rc = cli_main(["--trace", "--entrypoints",
                   "trace_programs:bad_entrypoints", "--format", "json"])
    assert rc == 1
    out = capsys.readouterr().out.strip().splitlines()
    import json as json_lib

    rules = {json_lib.loads(line)["rule"] for line in out if line}
    assert {"DP201", "DP202", "DP203", "DP204", "DP205", "DP206"} <= rules


def test_cli_trace_select(capsys):
    rc = cli_main(["--trace", "--select", "DP203", "--entrypoints",
                   "trace_programs:bad_entrypoints"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "DP203" in out and "DP205" not in out
    assert cli_main(["--trace", "--select", "DP999"]) == 2


def test_cli_trace_bad_entrypoints_spec():
    assert cli_main(["--trace", "--entrypoints", "no.such.module:x"]) == 2


@pytest.mark.slow
def test_cli_trace_production_subprocess(tmp_path):
    """The run_tests.sh gate end-to-end: `--trace` enumerates and audits
    the real production registry in a fresh process and exits 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "dorpatch_tpu.analysis", "--trace"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin",
             "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
             "HOME": str(tmp_path)})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stderr
