"""Streaming input path (`data.py`): the background chunked loader and the
double-buffered host->device prefetcher the 224-scale certify benches,
serve warmup and farm sweeps consume.

Contracts under test: order preservation through the worker thread, loader
errors re-raised at the consumer, prompt worker shutdown when the consumer
abandons the stream mid-flight, prefetch overlap visible in the telemetry
(the `data.prefetch` span for batch N+1 lands before the consumer touches
batch N), and the composed `streaming_batches` yielding device-resident
images end to end.
"""

import itertools
import json
import threading
import time

import jax
import numpy as np

from dorpatch_tpu import data as data_lib
from dorpatch_tpu import observe


def _numbered_batches(n, delay=0.0):
    for i in range(n):
        if delay:
            time.sleep(delay)
        yield (np.full((2, 4, 4, 3), i, np.float32),
               np.full((2,), i, np.int64))


def test_stream_batches_order_preserved():
    """16 batches through the worker thread, with producer jitter: every
    batch arrives, in order."""
    def jittery():
        for i, item in enumerate(_numbered_batches(16)):
            time.sleep(0.002 if i % 3 else 0.0)
            yield item

    got = [int(y[0]) for _x, y in data_lib.stream_batches(jittery(), depth=2)]
    assert got == list(range(16))


def test_stream_batches_propagates_loader_error():
    """A loader crash mid-stream surfaces at the consumer, after the
    batches that preceded it."""
    def broken():
        yield from _numbered_batches(3)
        raise RuntimeError("disk ate the shard")

    it = data_lib.stream_batches(broken(), depth=2)
    seen = []
    try:
        for _x, y in it:
            seen.append(int(y[0]))
        raise AssertionError("loader error never surfaced")
    except RuntimeError as e:
        assert "disk ate the shard" in str(e)
    assert seen == [0, 1, 2]


def test_stream_batches_clean_shutdown_midstream():
    """Closing the generator after a few batches stops the worker thread
    promptly — even though it is blocked on a full queue — and halts the
    underlying producer."""
    produced = []

    def endless():
        for i in itertools.count():
            produced.append(i)
            yield (np.zeros((1, 2, 2, 3), np.float32),
                   np.asarray([i], np.int64))

    gen = data_lib.stream_batches(endless(), depth=2)
    for _ in range(3):
        next(gen)
    gen.close()  # runs the finally block: stop, drain, join
    alive = [t for t in threading.enumerate()
             if t.name == "dorpatch-data-stream" and t.is_alive()]
    assert not alive
    n = len(produced)
    time.sleep(0.1)
    assert len(produced) == n  # producer really stopped


def test_prefetch_overlap_visible_in_events(tmp_path):
    """The overlap evidence the report reads: with depth=2, the
    `data.prefetch` span for batch N+1 is recorded BEFORE the consumer
    processes batch N — placement runs ahead of compute."""
    path = str(tmp_path / "events.jsonl")
    elog = observe.EventLog(path, run_id="r")
    with elog, observe.active(elog):
        for i, (x, y) in enumerate(data_lib.prefetch_to_device(
                _numbered_batches(6), depth=2)):
            assert isinstance(x, jax.Array)
            assert float(x[0, 0, 0, 0]) == i  # order survives placement
            observe.record_event("consume", batch=i)
    rows = [json.loads(line) for line in open(path)]
    order = [(r["name"], r.get("batch")) for r in rows
             if (r["kind"] == "span" and r["name"] == "data.prefetch")
             or (r["kind"] == "event" and r["name"] == "consume")]
    for n in range(5):
        assert order.index(("data.prefetch", n + 1)) \
            < order.index(("consume", n)), f"no lookahead at batch {n}"
    # every prefetch span carries its queue depth at dispatch time
    aheads = [r["ahead"] for r in rows if r.get("name") == "data.prefetch"
              and r["kind"] == "span"]
    assert max(aheads) >= 1


def test_stream_wait_events_recorded(tmp_path):
    """Each consumed batch records how long the consumer blocked on the
    loader thread (`data.stream.wait`) — near zero when the worker keeps
    ahead, the signal the streaming telemetry is for."""
    path = str(tmp_path / "events.jsonl")
    elog = observe.EventLog(path, run_id="r")
    with elog, observe.active(elog):
        out = list(data_lib.stream_batches(_numbered_batches(4), depth=2))
    assert len(out) == 4
    rows = [json.loads(line) for line in open(path)]
    waits = [r for r in rows if r.get("name") == "data.stream.wait"]
    assert [w["batch"] for w in waits] == [0, 1, 2, 3]
    assert all(w["wait_s"] >= 0.0 for w in waits)


def test_streaming_batches_end_to_end_synthetic():
    """The composed path over the synthetic source: device-resident
    images, host labels, stable shapes — what the certify bench loop
    consumes."""
    it = data_lib.streaming_batches("cifar10", data_dir="", batch_size=4,
                                    img_size=32, source="synthetic")
    batches = list(itertools.islice(it, 3))
    it.close()
    assert len(batches) == 3
    for x, y in batches:
        assert isinstance(x, jax.Array)
        assert x.shape == (4, 32, 32, 3) and x.dtype == np.float32
        assert isinstance(y, np.ndarray) and y.shape == (4,)
