"""Mesh-parallel tests on the virtual 8-device CPU mesh (SURVEY.md §4:
scale-free distributed testing). The load-bearing property: sharding is a
*placement* decision — sharded and unsharded runs compute the same program,
so results must match to float tolerance."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dorpatch_tpu import parallel
from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.config import AttackConfig, DefenseConfig
from dorpatch_tpu.defense import build_defenses
from dorpatch_tpu.parallel import (
    make_mesh,
    make_sharded_attack,
    make_sharded_defenses,
    place_batch,
    shard_apply_fn,
)


def _toy_apply(params, x):
    s = x.mean(axis=(1, 2))  # [B,3]
    logits = jnp.stack([s[:, 0], s[:, 1], s[:, 2], s.sum(-1) / 3.0], axis=-1)
    return logits * 10


def test_make_mesh_shapes():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(2, 4)
    assert mesh.axis_names == ("data", "mask")
    assert mesh.devices.shape == (2, 4)
    # mask=-1 absorbs the remainder
    assert make_mesh(2).devices.shape == (2, 4)
    assert make_mesh().devices.shape == (1, 8)
    with pytest.raises(ValueError):
        make_mesh(3)
    with pytest.raises(ValueError):
        make_mesh(4, 4)


def test_shard_apply_fn_preserves_values():
    mesh = make_mesh(1, 8)
    x = jax.random.uniform(jax.random.PRNGKey(0), (16, 8, 8, 3))
    ref = _toy_apply(None, x)
    sharded = jax.jit(shard_apply_fn(_toy_apply, mesh))(None, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(sharded), atol=1e-6)
    # output stays usable and correctly shaped
    assert sharded.shape == (16, 4)


def test_place_batch_shards_data_axis():
    mesh = make_mesh(2, 4)
    x = jnp.zeros((4, 8, 8, 3))
    y = jnp.zeros((4,), jnp.int32)
    xs, ys = place_batch(mesh, x, y)
    assert xs.sharding.spec == jax.sharding.PartitionSpec("data", None, None, None)
    assert ys.sharding.spec == jax.sharding.PartitionSpec("data")


@pytest.mark.slow
def test_sharded_attack_matches_unsharded():
    """Same seeds, same config: the 8-way-sharded attack must produce the
    same patch as the single-device run (same XLA program modulo layout)."""
    cfg = AttackConfig(
        sampling_size=8,
        max_iterations=8,
        sweep_interval=4,
        switch_iteration=4,
        failure_sampling_start=4,
        dropout=1,
        patch_budget=0.15,
        basic_unit=4,
        lr=0.05,
    )
    x = jax.random.uniform(jax.random.PRNGKey(2), (2, 32, 32, 3)) * 0.2
    key = jax.random.PRNGKey(3)

    ref = DorPatch(_toy_apply, None, 4, cfg, remat=False).generate(x, key=key)

    mesh = make_mesh(2, 4)
    atk = make_sharded_attack(_toy_apply, None, 4, cfg, mesh, remat=False)
    xs = place_batch(mesh, x)
    out = atk.generate(xs, key=key)

    np.testing.assert_allclose(
        np.asarray(ref.adv_mask), np.asarray(out.adv_mask), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ref.adv_pattern), np.asarray(out.adv_pattern), atol=1e-5)
    np.testing.assert_array_equal(ref.y, out.y)


@pytest.mark.slow
def test_sharded_defense_matches_unsharded():
    dcfg = DefenseConfig(ratios=(0.06,), chunk_size=16)
    x = jax.random.uniform(jax.random.PRNGKey(7), (3, 32, 32, 3))

    ref = build_defenses(_toy_apply, 32, dcfg)[0]
    # full-table comparison needs the exhaustive schedule on BOTH sides —
    # explicit prune="off" (the meshed path runs the pruned schedule by
    # default now, same as single-chip)
    ref_records = ref.robust_predict(None, x, 4, prune="off")

    mesh = make_mesh(1, 8)
    sh = make_sharded_defenses(_toy_apply, 32, mesh, dcfg)[0]
    xs = jax.device_put(x, parallel.replicated(mesh))
    sh_records = sh.robust_predict(None, xs, 4, prune="off")

    for a, b in zip(ref_records, sh_records):
        assert a.prediction == b.prediction
        assert a.certification == b.certification
        np.testing.assert_array_equal(a.preds_1, b.preds_1)
        np.testing.assert_array_equal(a.preds_2, b.preds_2)

    # the meshed pruned DEFAULT agrees with the exhaustive meshed verdicts
    # wherever it evaluated the table (bit-identical verdicts, sparse
    # preds_2) — test_defense.py's sharded-pruned section holds the full
    # parity/forwards contract against the single-chip pruned oracle
    pruned_records = sh.robust_predict(None, xs, 4)
    for a, b in zip(pruned_records, sh_records):
        assert a.prediction == b.prediction
        assert a.certification == b.certification
        np.testing.assert_array_equal(a.preds_1, b.preds_1)
        evaluated = np.asarray(a.preds_2) >= 0
        np.testing.assert_array_equal(np.asarray(a.preds_2)[evaluated],
                                      np.asarray(b.preds_2)[evaluated])


def test_mesh_certify_resolves_pruned():
    """The mesh restriction is gone: a sharded certifier resolves the
    pruned fast path (and the incremental rider) exactly like single-chip
    — no silent downgrade to the exhaustive schedule."""
    sh = make_sharded_defenses(
        _toy_apply, 32, make_mesh(2, 4),
        DefenseConfig(ratios=(0.06,), prune="exact", chunk_size=16))[0]
    assert sh.resolved_prune() == "exact"
    assert sh.resolved_prune("consensus") == "consensus"
    # phase-2 programs exist and plan at [S * bucket] wave shapes
    assert sh.row_bucket_sizes
    assert sh.mesh is not None


@pytest.mark.slow
def test_pipeline_uses_mesh(tmp_path):
    """run_experiment with mesh knobs runs the sharded path end-to-end."""
    from dorpatch_tpu.config import ExperimentConfig
    from dorpatch_tpu.pipeline import run_experiment

    cfg = ExperimentConfig(
        dataset="cifar10",
        base_arch="resnet18",
        batch_size=2,
        num_batches=1,
        synthetic_data=True,
        img_size=32,
        results_root=str(tmp_path / "results"),
        mesh_data=1,
        mesh_mask=8,
        attack=AttackConfig(
            sampling_size=8, max_iterations=4, sweep_interval=2,
            switch_iteration=2, dropout=1, basic_unit=4, patch_budget=0.15,
        ),
        defense=DefenseConfig(ratios=(0.06,), chunk_size=8),
    )
    m = run_experiment(cfg, verbose=False)
    assert m["evaluated_images"] > 0
    assert len(m["acc_pc"]) == 1


# ---------- shard_map-wrapped Pallas kernel on the mesh ----------

def test_sharded_pallas_masked_fill_matches_reference():
    """The shard_map Pallas path (interpret mode on the CPU mesh) must equal
    the jnp reference in both the primal and the image cotangent."""
    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.ops import masked_fill
    from dorpatch_tpu.ops.masked_fill import masked_fill_reference

    mesh = make_mesh(2, 4)
    key = jax.random.PRNGKey(0)
    imgs = jax.random.uniform(key, (4, 16, 16, 3))
    rects = jnp.asarray(masks_lib.dropout_universe(16, 1, (0.06,)))[:8]

    ref = masked_fill_reference(imgs, rects, 0.5)
    out = jax.jit(lambda im, rc: masked_fill(
        im, rc, 0.5, "interpret", mesh=mesh))(imgs, rects)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))

    def loss_sm(im):
        return jnp.sum(jnp.sin(masked_fill(im, rects, 0.5, "interpret", mesh=mesh)))

    def loss_ref(im):
        return jnp.sum(jnp.sin(masked_fill_reference(im, rects, 0.5)))

    g_sm = jax.jit(jax.grad(loss_sm))(imgs)
    g_ref = jax.grad(loss_ref)(imgs)
    np.testing.assert_allclose(np.asarray(g_sm), np.asarray(g_ref), atol=1e-5)


def test_sharded_pallas_indivisible_falls_back():
    """Shapes the mesh does not divide quietly use the XLA path (same math)."""
    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.ops import masked_fill
    from dorpatch_tpu.ops.masked_fill import masked_fill_reference

    mesh = make_mesh(2, 4)
    imgs = jax.random.uniform(jax.random.PRNGKey(1), (3, 16, 16, 3))  # 3 % 2 != 0
    rects = jnp.asarray(masks_lib.dropout_universe(16, 1, (0.06,)))[:5]  # 5 % 4 != 0
    out = masked_fill(imgs, rects, 0.5, "interpret", mesh=mesh)
    ref = masked_fill_reference(imgs, rects, 0.5)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


@pytest.mark.slow
def test_sharded_attack_with_pallas_interpret_matches_unsharded():
    """VERDICT r2 ask #5: use_pallas is legal under a mesh — the sharded
    attack with the interpret-mode Pallas kernel must match the unsharded
    reference-path attack bit-for-bit (placement-only difference)."""
    cfg = AttackConfig(
        sampling_size=8, max_iterations=4, sweep_interval=2,
        switch_iteration=2, failure_sampling_start=2, dropout=1,
        patch_budget=0.15, basic_unit=4, lr=0.05,
    )
    x = jax.random.uniform(jax.random.PRNGKey(2), (2, 32, 32, 3)) * 0.2
    key = jax.random.PRNGKey(3)

    ref = DorPatch(_toy_apply, None, 4, cfg, remat=False).generate(x, key=key)

    import dataclasses
    cfg_pl = dataclasses.replace(cfg, use_pallas="interpret")
    mesh = make_mesh(2, 4)
    atk = make_sharded_attack(_toy_apply, None, 4, cfg_pl, mesh, remat=False)
    assert atk.mesh is mesh
    out = atk.generate(place_batch(mesh, x), key=key)

    np.testing.assert_allclose(
        np.asarray(ref.adv_mask), np.asarray(out.adv_mask), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ref.adv_pattern), np.asarray(out.adv_pattern), atol=1e-5)
    np.testing.assert_array_equal(ref.y, out.y)


# ---------- multi-host feeding ----------

def test_place_batch_multihost_single_process_matches_place_batch():
    """`place_batch_multihost` assembles a global array from per-process
    shards (`jax.make_array_from_process_local_data`). With one process the
    local shard IS the global batch: sharding and values must match
    `place_batch` exactly."""
    mesh = make_mesh(2, 4)
    x = np.asarray(jax.random.uniform(jax.random.PRNGKey(0), (4, 8, 8, 3)))
    y = np.arange(4, dtype=np.int32)

    xg, yg = parallel.place_batch_multihost(mesh, x, y)
    assert xg.shape == (4, 8, 8, 3)
    assert xg.sharding.spec == jax.sharding.PartitionSpec("data", None, None, None)
    assert yg.sharding.spec == jax.sharding.PartitionSpec("data")
    xr, yr = place_batch(mesh, jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_array_equal(np.asarray(xg), np.asarray(xr))
    np.testing.assert_array_equal(np.asarray(yg), np.asarray(yr))

    # a computation over the assembled batch behaves like the local one
    out = jax.jit(lambda a: a.sum(axis=(1, 2, 3)))(xg)
    np.testing.assert_allclose(np.asarray(out), x.sum(axis=(1, 2, 3)), rtol=1e-6)


def test_place_batch_multihost_rejects_misaligned_per_image():
    mesh = make_mesh(2, 4)
    x = np.zeros((4, 8, 8, 3), np.float32)
    with pytest.raises(ValueError):
        parallel.place_batch_multihost(mesh, x, np.zeros((3,), np.int32))


@pytest.mark.slow
def test_process0store_single_process_round_trip(tmp_path):
    """`Process0Store`'s broadcast protocol (presence header -> padded
    shape vector -> values) degenerates to identity on one process, so the
    whole adapter is unit-testable here: reads must round-trip what the
    wrapped store saved, misses must return None, and the PC-record cache
    must always miss (multi-process recomputes certification)."""
    from dorpatch_tpu.artifacts import ArtifactStore
    from dorpatch_tpu.parallel.multiproc import Process0Store

    store = Process0Store(ArtifactStore(str(tmp_path / "r" / "sub")))
    assert store.load_patch(0) is None
    assert store.load_stage0(0) is None
    assert store.load_targets(0) is None

    mask = np.random.default_rng(0).random((3, 8, 8, 1)).astype(np.float32)
    pattern = np.random.default_rng(1).random((3, 8, 8, 3)).astype(np.float32)
    store.save_patch(0, mask, pattern)
    got_m, got_p = store.load_patch(0)
    np.testing.assert_allclose(got_m, mask, rtol=1e-6)
    np.testing.assert_allclose(got_p, pattern, rtol=1e-6)

    store.save_targets(0, np.array([5, 1, 3], np.int32))
    t = store.load_targets(0)
    assert t.tolist() == [5, 1, 3]
    assert store.resolve_targets(0, None).tolist() == [5, 1, 3]

    store.save_stage0(1, mask, pattern)
    s0 = store.load_stage0(1)
    np.testing.assert_allclose(s0[0], mask, rtol=1e-6)
    # recorded targets absent AND stage0 present: rederivation closure runs
    got = store.resolve_targets(1, lambda s: np.array([9] * s[0].shape[0]))
    assert got.tolist() == [9, 9, 9]

    store.save_pc_records(0, [["rec"]])
    assert store.load_pc_records(0) is None  # by design: recompute
    # ...but the underlying store kept them for single-process reuse
    assert store.store.load_pc_records(0) == [["rec"]]


# Known XLA limitation: the child processes always run the CPU backend (no
# accelerator plugin, JAX_PLATFORMS stripped), and any cross-process
# computation there dies in the XLA CPU client with
# `XlaRuntimeError: INVALID_ARGUMENT: Multiprocess computations aren't
# implemented on the CPU backend.` — multi-process CPU execution is
# unsupported upstream (see the "Multiprocess computations" check in
# openxla's pjrt CPU client and the supported-backends table in
# https://jax.readthedocs.io/en/latest/multi_process.html). The test is
# kept (it documents the intended multihost feeding path and runs as-is on
# real multi-host TPU) but skipped on the CPU-only suite so tier-1 signal
# stays clean; drop the marker when jaxlib ships CPU cross-process
# collectives.
@pytest.mark.skip(
    reason="multi-process computations unsupported on the XLA CPU backend")
def test_two_process_multihost_feeding():
    """True 2-process multi-host run on CPU (VERDICT r2 ask #9): two
    jax.distributed processes, 4 virtual devices each, assemble a global
    batch from per-process shards via place_batch_multihost and run a
    sharded attack block over the joint (2,4) mesh. See multihost_child.py
    for the assertions."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])

    child = os.path.join(os.path.dirname(__file__), "multihost_child.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PALLAS_AXON_POOL_IPS"] = ""  # no accelerator plugin in children
    procs = [
        subprocess.Popen([sys.executable, child, str(i), port], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2000:]}"
        assert f"proc {i}: OK" in out


# Same known XLA limitation as test_two_process_multihost_feeding above:
# the child processes run the CPU backend and the cross-process computation
# dies with `XlaRuntimeError: INVALID_ARGUMENT: Multiprocess computations
# aren't implemented on the CPU backend.` (see the supported-backends table
# in https://jax.readthedocs.io/en/latest/multi_process.html). Kept for the
# real multi-host TPU path, skipped on the CPU-only suite; drop the marker
# when jaxlib ships CPU cross-process collectives.
@pytest.mark.skip(
    reason="multi-process computations unsupported on the XLA CPU backend")
@pytest.mark.slow
def test_two_process_experiment_driver(tmp_path):
    """Full `run_experiment` under jax.distributed (BASELINE config 5's
    last gap, r04 verdict weak #7): 2 processes x 4 virtual devices run the
    SPMD driver — replicated per-image state, masked batch sharded over the
    joint mesh, process-0-only artifacts with broadcast cache reads — twice
    (fresh + resumed). Asserts identical reports across processes and runs,
    and that only process 0 wrote artifacts."""
    import glob as glob_mod
    import json
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])

    child = os.path.join(os.path.dirname(__file__), "multihost_driver_child.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PALLAS_AXON_POOL_IPS"] = ""
    procs = [
        subprocess.Popen([sys.executable, child, str(i), port,
                          str(tmp_path / "results")], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = {}
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith(f"RESULT {i} "):
                results[i] = json.loads(line.split(" ", 2)[2])
    assert set(results) == {0, 1}, outs
    # identical host values on every process: the reports must agree
    # exactly, fresh and resumed
    assert results[0]["report1"] == results[1]["report1"]
    assert results[0]["report2"] == results[1]["report2"]
    assert results[0]["report1"] == results[0]["report2"]  # resume scored same
    assert results[0]["evaluated"] >= 1
    # the resumed run loaded cached patches: no attack was re-run
    assert results[0]["resumed_attack_seconds"] is False
    # artifacts written by process 0 only (both processes share this
    # filesystem, so double-writes would be races): exactly two copies —
    # the final per-budget patch and the stage-0 artifact its parent dir
    # shares across budgets (ArtifactStore.save_stage0)
    pts = glob_mod.glob(str(tmp_path / "results" / "**" / "adv_mask_*.pt"),
                        recursive=True)
    assert len(pts) == 2, pts
    assert len({os.path.dirname(p) for p in pts}) == 2, pts


def test_sharded_block_hlo_has_allreduce_no_big_allgather():
    """GSPMD-regression guard (r03 verdict #7): the compiled sharded attack
    block must contain the mask-axis all-reduce (the loss/grad contraction
    `shard_apply_fn` exists to produce) and must NOT all-gather the masked
    `[B*S, H, W, C]` tensor — the replicate-everything pathology the
    sharding constraint prevents. Static HLO proof in the spirit of
    test_conv_policy_skips_conv_recompute_in_hlo."""
    import re

    from dorpatch_tpu import losses, masks as masks_lib
    from dorpatch_tpu.models.small import CifarResNet18

    img, batch, eot = 32, 2, 8
    model = CifarResNet18(num_classes=10)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, img, img, 3)))
    cfg = AttackConfig(sampling_size=eot, dropout=1, dropout_sizes=(0.06,),
                       basic_unit=4)
    mesh = make_mesh(1, 8)
    atk = make_sharded_attack(model.apply, params, 10, cfg, mesh, remat=False)

    universe = jnp.asarray(masks_lib.dropout_universe(
        img, cfg.dropout, cfg.dropout_sizes))
    key = jax.random.PRNGKey(1)
    x = place_batch(mesh, jax.random.uniform(key, (batch, img, img, 3)))
    y = jnp.zeros((batch,), jnp.int32)
    local_var_x = jnp.mean(losses.local_variance(x)[0], axis=-1)
    state = atk._init_state(key, x, y, False, universe.shape[0])

    block = atk._get_block(1, img, 2)
    txt = block.lower(state, x, local_var_x, universe).compile().as_text()

    assert "all-reduce" in txt, "mask-axis loss/grad all-reduce missing"

    # No all-gather may materialize anything as large as the full masked
    # tensor (B*S*H*W*C elements); small gathers (logits, bookkeeping
    # vectors) are legitimate.
    full_masked = batch * eot * img * img * 3
    gathered = []
    for line in txt.splitlines():
        if "all-gather(" not in line and "all-gather-start(" not in line:
            continue
        # HLO result shape sits after '=': `%name = f32[16,32,32,3]{...} all-gather(...)`
        m = re.search(r"=\s*\(?\s*\w+\[([\d,]*)\]", line)
        assert m, f"unparsed all-gather line: {line.strip()[:200]}"
        dims = [int(d) for d in m.group(1).split(",") if d]
        gathered.append((int(np.prod(dims)) if dims else 1, line.strip()))
    big = [g for g in gathered if g[0] >= full_masked]
    assert not big, f"all-gather of masked-tensor scale: {big[0][1][:200]}"
