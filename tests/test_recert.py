"""Continuous re-certification: baseline diff rules (DP400-DP402),
crash-resumable scheduler generations, serve boot gate, CLI contract.

Fast tests drive the scheduler with stub farm runners (no model build); the
full pipeline + SIGKILL resume is `tools/recert_smoke.py`'s job.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dorpatch_tpu.config import RecertConfig
from dorpatch_tpu.farm.queue import JobQueue
from dorpatch_tpu.farm.worker import FarmWorker
from dorpatch_tpu.recert import baseline as rb
from dorpatch_tpu.recert.__main__ import main as recert_main
from dorpatch_tpu.recert.gate import RecertGateError, boot_gate, snapshot
from dorpatch_tpu.recert.scheduler import (
    RecertError, RecertScheduler, is_recert_dir)
from dorpatch_tpu.sweep import append_row

SPEC = {
    "base": {"dataset": "cifar10", "base_arch": "resnet18", "img_size": 32,
             "batch_size": 2, "synthetic_data": True},
    "axes": {"attack.patch_budget": [0.06, 0.12]},
    "sweep": {"densities": [0.0], "structureds": [1e-3],
              "defense_ratio": 0.06},
    "max_attempts": 2,
}

JOB = {"base": SPEC["base"], "sweep": SPEC["sweep"],
       "params": {"attack.patch_budget": 0.06}}


def stub_runner(ra=50.0, asr=25.0):
    """A farm runner writing one plausible sweep row per job."""
    def runner(job, ctx):
        append_row(ctx.result_dir, {
            "patch_budget": job["params"]["attack.patch_budget"],
            "density": 0.0, "structured": 1e-3,
            "robust_accuracy": ra, "certified_asr_pc": asr,
            "asr": 100.0 - ra, "point": 0, "images": 2})
        return {"rows": 1}
    return runner


def _drain(farm_dir, runner, worker_id="w"):
    FarmWorker(str(farm_dir), worker_id=worker_id, lease_ttl=10.0,
               poll_interval=0.02, heartbeat_interval=0.2,
               backoff_base=0.05, backoff_cap=0.2, runner=runner).run()


def _cycle(sched, spec=None, ra=50.0, update=False, runner=None):
    gen, farm_dir = sched.begin_generation(spec)
    _drain(farm_dir, runner or stub_runner(ra=ra))
    return gen, sched.complete_generation(gen, farm_dir,
                                          update_baseline=update)


# ---------------- cell keys / measurements ----------------


def test_cell_key_json_roundtrip_stable():
    # a spec float and the same float recorded through rows.jsonl must
    # produce the same key, or every generation would look like grid drift
    row = {"patch_budget": 0.06, "density": 0.0, "structured": 1e-3}
    recorded = json.loads(json.dumps(row))
    assert rb.cell_key(JOB, row) == rb.cell_key(JOB, recorded)
    key = rb.cell_key(JOB, row)
    assert key.startswith("resnet18@cifar10/32|pc:r0.06|")
    assert "patch_budget=0.06" in key


def test_cell_key_carries_non_grid_axis_params():
    a = dict(JOB, params={"attack.patch_budget": 0.06, "attack.dropout": 1})
    b = dict(JOB, params={"attack.patch_budget": 0.06, "attack.dropout": 2})
    row = {"patch_budget": 0.06, "density": 0.0, "structured": 1e-3}
    assert rb.cell_key(a, row) != rb.cell_key(b, row)


def test_job_cells_enumerable_without_rows():
    job = {**JOB, "sweep": {**SPEC["sweep"], "patch_budgets": [0.06, 0.12]}}
    cells = rb.job_cells(job)
    assert len(cells) == 2 and len(set(cells)) == 2


def test_fold_and_dump_deterministic():
    measured = {"k1": {"robust_accuracy": 51.5, "certified_asr_pc": 20.0,
                       "images": 4, "job": "j"}}
    d1 = rb.fold_measurements(None, measured, 3)
    d2 = rb.fold_measurements(rb.empty_baseline(), dict(measured), 3)
    assert rb.dump_baseline(d1) == rb.dump_baseline(d2)
    assert d1["entries"]["k1"]["generation"] == 3
    assert d1["generation"] == 3
    # folding on top preserves unmeasured entries and per-cell overrides
    d1["entries"]["k2"] = {"robust_accuracy": 70.0, "certified_asr_pc": 5.0,
                           "tolerance": 5.0}
    d3 = rb.fold_measurements(d1, measured, 4)
    assert d3["entries"]["k2"]["robust_accuracy"] == 70.0
    assert d3["entries"]["k1"]["generation"] == 4


def _seeded(ra=50.0, asr=25.0, tol=None):
    entry = {"robust_accuracy": ra, "certified_asr_pc": asr, "images": 2,
             "generation": 1}
    if tol is not None:
        entry["tolerance"] = tol
    return {"version": 1, "generation": 1, "tolerance_default": 2.0,
            "entries": {"cellA": entry}}


def _m(ra=50.0, asr=25.0):
    return {"robust_accuracy": ra, "certified_asr_pc": asr, "images": 2,
            "job": "j"}


def test_check_unseeded_baseline_is_dp402():
    fs = rb.check_measurements({"cellA": _m()}, [], None, 1)
    # ...and the fresh cell also reads as DP401 added vs the empty set
    assert {f.rule_id for f in fs} == {"DP401", "DP402"}
    unseeded = [f for f in fs if f.rule_id == "DP402"]
    assert len(unseeded) == 1 and "<unseeded>" in unseeded[0].message


def test_check_regression_and_asr_rules():
    data = _seeded(ra=50.0, asr=25.0)
    assert rb.check_measurements({"cellA": _m(ra=48.5)}, [], data, 2) == []
    fs = rb.check_measurements({"cellA": _m(ra=47.0)}, [], data, 2)
    assert [f.rule_id for f in fs] == ["DP400"]
    assert "50.00% -> 47.00%" in fs[0].message
    # robust accuracy inside tolerance, certified ASR eroding past it
    fs = rb.check_measurements({"cellA": _m(ra=50.0, asr=28.0)}, [], data, 2)
    assert [f.rule_id for f in fs] == ["DP400"]
    assert "certified attack success rose" in fs[0].message


def test_check_per_cell_tolerance_overrides_default():
    data = _seeded(ra=50.0, tol=10.0)
    assert rb.check_measurements({"cellA": _m(ra=42.0)}, [], data, 2) == []
    fs = rb.check_measurements({"cellA": _m(ra=39.0)}, [], data, 2)
    assert [f.rule_id for f in fs] == ["DP400"]


def test_check_grid_drift_and_holes():
    data = _seeded()
    fs = rb.check_measurements({"cellA": _m(), "cellB": _m()}, [], data, 2)
    assert [(f.rule_id, "cellB" in f.message) for f in fs] == [("DP401", True)]
    fs = rb.check_measurements({}, [], data, 2)  # cellA gone from the grid
    assert [f.rule_id for f in fs] == ["DP401"]
    assert "--allow-remove" in fs[0].message
    fs = rb.check_measurements({}, ["cellA"], data, 5)  # covered, unmeasured
    assert [f.rule_id for f in fs] == ["DP402"]
    assert "4 generation(s) old" in fs[0].message


def test_check_allowlist_and_select():
    data = _seeded()
    measured = {"cellA": _m(ra=40.0), "cellB": _m()}
    fs = rb.check_measurements(measured, [], data, 2)
    assert {f.rule_id for f in fs} == {"DP400", "DP401"}
    allow = {"cellA": {"DP400": "known noisy cell"}}
    fs = rb.check_measurements(measured, [], data, 2, allow=allow)
    assert [f.rule_id for f in fs] == ["DP401"]
    fs = rb.check_measurements(measured, [], data, 2, select=["DP400"])
    assert [f.rule_id for f in fs] == ["DP400"]


def test_build_verdict_statuses_and_margin():
    data = _seeded(ra=50.0)
    fs = rb.check_measurements({"cellA": _m(ra=47.0)}, [], data, 2)
    v = rb.build_verdict({"cellA": _m(ra=47.0)}, [], data, 2, fs)
    assert v["status"] == "failing"
    assert v["cells"]["cellA"]["status"] == "regressed"
    assert v["worst_margin"] == pytest.approx(-1.0)
    v = rb.build_verdict({"cellA": _m(ra=49.0)}, [], data, 2, [])
    assert v["status"] == "ok" and v["worst_margin"] == pytest.approx(1.0)
    fs = rb.check_measurements({}, ["cellA"], data, 2)
    v = rb.build_verdict({}, ["cellA"], data, 2, fs)
    assert v["status"] == "stale" and v["cells"]["cellA"]["status"] == "stale"


# ---------------- scheduler generations ----------------


def test_scheduler_full_cycle_seeds_then_stays_ok(tmp_path):
    sched = RecertScheduler(str(tmp_path / "recert"),
                            baseline_file=str(tmp_path / "rb.json"))
    gen, verdict = _cycle(sched, SPEC, update=True)
    assert gen == 1 and verdict["status"] == "ok"
    assert len(verdict["cells"]) == 2
    assert is_recert_dir(str(tmp_path / "recert"))
    # second generation, same numbers, no update: clean against the seed
    gen, verdict = _cycle(sched, SPEC)
    assert gen == 2 and verdict["status"] == "ok" and verdict["clean"]
    assert verdict["worst_margin"] == pytest.approx(2.0)


def test_scheduler_resumes_inflight_generation_not_resubmit(tmp_path):
    sched = RecertScheduler(str(tmp_path / "recert"),
                            baseline_file=str(tmp_path / "rb.json"))
    gen, farm_dir = sched.begin_generation(SPEC)
    # crash before completion: a new scheduler instance (fresh process)
    # must resume THIS generation — spec comes from the inflight record
    sched2 = RecertScheduler(str(tmp_path / "recert"),
                             baseline_file=str(tmp_path / "rb.json"))
    gen2, farm_dir2 = sched2.begin_generation()
    assert (gen2, farm_dir2) == (gen, farm_dir)
    assert JobQueue(farm_dir2).counts()["total"] == 2
    _drain(farm_dir2, stub_runner())
    verdict = sched2.complete_generation(gen2, farm_dir2,
                                         update_baseline=True)
    assert verdict["generation"] == gen
    # after completion a begin without a spec has nothing to run
    with pytest.raises(RecertError):
        sched2.begin_generation()


def test_scheduler_recovers_from_torn_state_file(tmp_path):
    sched = RecertScheduler(str(tmp_path / "recert"),
                            baseline_file=str(tmp_path / "rb.json"))
    _cycle(sched, SPEC, update=True)
    gen, farm_dir = sched.begin_generation(SPEC)
    state_path = sched.state_path
    raw = open(state_path, "rb").read()
    with open(state_path, "wb") as fh:  # torn mid-write by a crash
        fh.write(raw[:len(raw) // 2])
    sched3 = RecertScheduler(str(tmp_path / "recert"),
                             baseline_file=str(tmp_path / "rb.json"))
    st = sched3.load_state()
    assert st["generation"] == 1  # healed from the gen dirs on disk
    assert st["inflight"]["generation"] == gen
    gen3, farm_dir3 = sched3.begin_generation()
    assert (gen3, farm_dir3) == (gen, farm_dir)


def test_quarantined_job_becomes_hole_generation_completes(tmp_path):
    sched = RecertScheduler(str(tmp_path / "recert"),
                            baseline_file=str(tmp_path / "rb.json"))
    _cycle(sched, SPEC, update=True)

    def half_bad(job, ctx):
        if job["params"]["attack.patch_budget"] == 0.12:
            raise ValueError("deterministic failure -> quarantine")
        return stub_runner()(job, ctx)

    gen, farm_dir = sched.begin_generation(SPEC)
    _drain(farm_dir, half_bad)
    assert sched.drained(farm_dir)  # quarantine never hangs the generation
    verdict = sched.complete_generation(gen, farm_dir)
    assert verdict["status"] == "stale"
    assert verdict["findings_by_rule"] == {"DP402": 1}
    stale = [k for k, c in verdict["cells"].items()
             if c["status"] == "stale"]
    assert len(stale) == 1 and "patch_budget=0.12" in stale[0]


def test_update_from_latest_refuses_shrink_without_allow_remove(tmp_path):
    sched = RecertScheduler(str(tmp_path / "recert"),
                            baseline_file=str(tmp_path / "rb.json"))
    _cycle(sched, SPEC, update=True)
    shrunk = dict(SPEC, axes={"attack.patch_budget": [0.06]})
    _cycle(sched, shrunk)
    before = open(sched.baseline_file, "rb").read()
    with pytest.raises(RecertError, match="--allow-remove"):
        sched.update_from_latest()
    assert open(sched.baseline_file, "rb").read() == before
    summary = sched.update_from_latest(allow_remove=True)
    assert len(summary["removed"]) == 1
    data = rb.load_baseline(sched.baseline_file)
    assert len(data["entries"]) == 1


def test_update_keeps_hole_cells(tmp_path):
    # a hole is a missing measurement, not a grid change: update must not
    # silently drop the cell's reference entry
    sched = RecertScheduler(str(tmp_path / "recert"),
                            baseline_file=str(tmp_path / "rb.json"))
    _cycle(sched, SPEC, update=True)

    def half_bad(job, ctx):
        if job["params"]["attack.patch_budget"] == 0.12:
            raise ValueError("boom")
        return stub_runner()(job, ctx)

    gen, farm_dir = sched.begin_generation(SPEC)
    _drain(farm_dir, half_bad)
    sched.complete_generation(gen, farm_dir)
    summary = sched.update_from_latest()  # no removal: holes are kept
    assert summary["removed"] == []
    data = rb.load_baseline(sched.baseline_file)
    assert len(data["entries"]) == 2


# ---------------- serve boot gate ----------------


def test_boot_gate_modes(tmp_path):
    assert boot_gate("", "off") is None
    with pytest.raises(ValueError):
        boot_gate("", "paranoid")
    with pytest.raises(RecertGateError):
        boot_gate("", "strict")  # a mode that reads a verdict needs a dir
    # absent verdict: warn degrades, strict refuses
    snap = boot_gate(str(tmp_path), "warn")
    assert snap["status"] == "absent"
    with pytest.raises(RecertGateError, match="absent"):
        boot_gate(str(tmp_path), "strict")


def test_boot_gate_reads_published_verdict(tmp_path):
    sched = RecertScheduler(str(tmp_path / "recert"),
                            baseline_file=str(tmp_path / "rb.json"))
    _cycle(sched, SPEC, update=True)
    snap = boot_gate(str(tmp_path / "recert"), "strict")
    assert snap["status"] == "ok" and snap["generation"] == 1
    # plant a regression: strict refuses naming the cell, warn carries it
    _cycle(sched, SPEC, ra=40.0)
    with pytest.raises(RecertGateError, match="patch_budget"):
        boot_gate(str(tmp_path / "recert"), "strict")
    snap = boot_gate(str(tmp_path / "recert"), "warn")
    assert snap["status"] == "failing"
    assert snap["findings_by_rule"] == {"DP400": 2}
    assert snapshot(str(tmp_path / "recert"))["status"] == "failing"


def test_service_boot_gate_strict_refuses_warn_serves(tmp_path):
    from dorpatch_tpu.config import DefenseConfig, ServeConfig
    from dorpatch_tpu.serve.service import CertifiedInferenceService

    sched = RecertScheduler(str(tmp_path / "recert"),
                            baseline_file=str(tmp_path / "rb.json"))
    _cycle(sched, SPEC, update=True)
    _cycle(sched, SPEC, ra=40.0)  # published verdict now failing

    def stub_apply(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    def make(require):
        return CertifiedInferenceService(
            stub_apply, None, num_classes=5, img_size=32,
            serve_cfg=ServeConfig(max_batch=2, bucket_sizes=(1, 2),
                                  replicas=1),
            defense_cfg=DefenseConfig(ratios=(0.1,), chunk_size=64),
            recert_cfg=RecertConfig(dir=str(tmp_path / "recert"),
                                    require=require))

    with pytest.raises(RecertGateError, match="failing"):
        make("strict").start()
    svc = make("warn").start()
    try:
        r = svc.robustness()
        assert r["status"] == "failing" and r["require"] == "warn"
        assert any(c.get("status") == "regressed"
                   for c in r["cells"].values())
        assert svc.stats()["robustness"]["status"] == "failing"
        resp = svc.predict(np.zeros((32, 32, 3), np.float32))
        assert resp.status == "ok"  # warn mode serves, loudly degraded
    finally:
        svc.stop()


def test_service_robustness_unconfigured():
    from dorpatch_tpu.serve.service import CertifiedInferenceService
    svc = CertifiedInferenceService.__new__(CertifiedInferenceService)
    svc._robustness = None
    assert svc.robustness() == {"require": "off", "status": "unconfigured"}


# ---------------- CLI contract ----------------


def test_cli_schedule_status_check_roundtrip(tmp_path, capsys):
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(SPEC))
    rdir = str(tmp_path / "recert")
    bfile = str(tmp_path / "rb.json")
    assert recert_main(["schedule", rdir, "--spec", str(spec_path),
                        "--baseline-file", bfile]) == 0
    capsys.readouterr()
    assert recert_main(["status", rdir, "--baseline-file", bfile]) == 0
    out = capsys.readouterr().out  # observe.log prefixes "[pN +T.Ts] "
    st = json.loads(out[out.index("{"):])
    assert st["inflight"]["generation"] == 1
    assert st["inflight"]["counts"]["total"] == 2

    # drain out-of-band (the CLI's in-process worker runs the real model
    # stack; unit tests use the stub runner), then check via the CLI
    sched = RecertScheduler(rdir, baseline_file=bfile)
    gen, farm_dir = sched.begin_generation()
    _drain(farm_dir, stub_runner())
    sched.complete_generation(gen, farm_dir, update_baseline=True)

    assert recert_main(["check", rdir, "--baseline-file", bfile]) == 0
    capsys.readouterr()

    # plant a regression generation: check exits 1 naming the cell
    gen, farm_dir = sched.begin_generation(SPEC)
    _drain(farm_dir, stub_runner(ra=40.0))
    sched.complete_generation(gen, farm_dir)
    capsys.readouterr()  # drop the out-of-band worker's log lines
    rc = recert_main(["check", rdir, "--baseline-file", bfile,
                      "--format", "json"])
    assert rc == 1
    out = capsys.readouterr().out.strip().splitlines()
    objs = [json.loads(line) for line in out]
    assert {o["rule"] for o in objs} == {"DP400"}
    assert all("patch_budget" in o["message"] for o in objs)

    # select filter validates rule ids (usage error -> 2)
    assert recert_main(["check", rdir, "--baseline-file", bfile,
                        "--select", "DP999"]) == 2


def test_cli_check_without_generation_is_usage_error(tmp_path):
    assert recert_main(["check", str(tmp_path / "empty")]) == 2
    assert recert_main(["run", str(tmp_path / "empty2")]) == 2  # no spec


def test_cli_update_refusal_exit_code(tmp_path, capsys):
    rdir = str(tmp_path / "recert")
    bfile = str(tmp_path / "rb.json")
    sched = RecertScheduler(rdir, baseline_file=bfile)
    _cycle(sched, SPEC, update=True)
    _cycle(sched, dict(SPEC, axes={"attack.patch_budget": [0.06]}))
    assert recert_main(["update", rdir, "--baseline-file", bfile]) == 1
    assert "--allow-remove" in capsys.readouterr().err
    assert recert_main(["update", rdir, "--baseline-file", bfile,
                        "--allow-remove"]) == 0


def test_cli_list_rules(capsys):
    assert recert_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("DP400", "DP401", "DP402"):
        assert rid in out


# ---------------- observe report dispatch ----------------


def test_report_cli_dispatches_on_recert_dir(tmp_path, capsys):
    from dorpatch_tpu.observe import report as report_cli

    rdir = str(tmp_path / "recert")
    sched = RecertScheduler(rdir, baseline_file=str(tmp_path / "rb.json"))
    _cycle(sched, SPEC, update=True)
    _cycle(sched, SPEC, ra=40.0)
    assert report_cli.main([rdir]) == 0
    out = capsys.readouterr().out
    assert "= DorPatch re-certification report =" in out
    assert "-- verdict" in out and "regressed" in out
    assert "DP400" in out
    assert report_cli.main([rdir, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["verdict"]["status"] == "failing"
    assert payload["status"]["generation"] == 2
