"""Child for the 2-process multi-process EXPERIMENT-DRIVER test.

Where `multihost_child.py` drives the attack API directly, this child runs
the full `pipeline.run_experiment` under `jax.distributed` — the SPMD
driver path (`parallel/multiproc.py`): replicated per-image state, masked
batch sharded over the joint (2,4) mesh, artifact IO on process 0 with
broadcast cache reads. Run twice (fresh, then resumed) to also exercise the
broadcast resume path: on the second run process 0 finds the cached patches
and process 1 (which has NO files) must take the same branch with the same
data.

Usage: multihost_driver_child.py <process_id> <coordinator_port> <results_root>
"""

import json
import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
results_root = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dorpatch_tpu.config import AttackConfig, DefenseConfig, ExperimentConfig  # noqa: E402
from dorpatch_tpu.pipeline import run_experiment  # noqa: E402

assert jax.process_count() == 2

cfg = ExperimentConfig(
    dataset="cifar10",
    base_arch="resnet18",
    img_size=32,
    batch_size=2,
    num_batches=1,
    synthetic_data=True,
    results_root=results_root,
    mesh_data=2,
    mesh_mask=4,
    metrics_log=False,
    # targeted=True so the resume run exercises the recorded-target
    # broadcast (Process0Store.load_targets), not just the patch cache
    attack=AttackConfig(targeted=True, sampling_size=4, max_iterations=2,
                        sweep_interval=2, switch_iteration=2, dropout=1,
                        dropout_sizes=(0.06,), basic_unit=4),
    defense=DefenseConfig(ratios=(0.06,), num_mask_per_axis=2, chunk_size=8),
)

m1 = run_experiment(cfg, verbose=False)
# second run: process 0 resumes from its artifacts; process 1 has the same
# view only through the broadcast reads
m2 = run_experiment(cfg, verbose=False)

print("RESULT", pid, json.dumps({
    "report1": m1["report"], "report2": m2["report"],
    "evaluated": m1["evaluated_images"],
    "resumed_attack_seconds": "attack_seconds" in m2,
}), flush=True)
