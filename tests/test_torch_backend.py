"""Torch oracle backend tests: loss/grad parity with the jax attack, verdict
equivalence, checkpoint-synced model parity, and the BASELINE acceptance
gate — certified-ASR parity of the two backends on fixed seeds/images."""

import os

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from dorpatch_tpu import losses as jlosses
from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.backends import torch_attack as ta
from dorpatch_tpu.config import AttackConfig, DefenseConfig, ExperimentConfig
from dorpatch_tpu.defense import double_masking_verdict, double_masking_verdict_np

RNG = np.random.default_rng(7)


def _rand(*shape):
    return RNG.uniform(0, 1, size=shape).astype(np.float32)


def _nchw(x):
    return torch.from_numpy(np.moveaxis(x, -1, 1).copy())


# ---------------- verdict twin ----------------

def test_verdict_np_matches_jnp():
    m, c = 9, 7
    p = m * (m - 1) // 2
    for trial in range(20):
        rng = np.random.default_rng(trial)
        # mostly-unanimous tables so all branches (certified, second-round
        # recovery, majority fallback) get exercised across trials
        base = rng.integers(0, c)
        p1 = np.full((3, m), base)
        p2 = np.full((3, p), base)
        flip = rng.random((3, m)) < 0.3
        p1[flip] = rng.integers(0, c, flip.sum())
        flip2 = rng.random((3, p)) < 0.3
        p2[flip2] = rng.integers(0, c, flip2.sum())
        got_p, got_c = double_masking_verdict_np(p1, p2, m, c)
        want_p, want_c = double_masking_verdict(
            jnp.asarray(p1), jnp.asarray(p2), m, c)
        np.testing.assert_array_equal(got_p, np.asarray(want_p))
        np.testing.assert_array_equal(got_c, np.asarray(want_c))


# ---------------- torch loss twins vs jax ----------------

def test_torch_losses_match_jax():
    x = _rand(2, 16, 16, 3)
    mask = _rand(2, 16, 16, 1)
    pattern = _rand(2, 16, 16, 3)
    xt, mt, pt = _nchw(x), _nchw(mask), _nchw(pattern)

    np.testing.assert_allclose(
        np.moveaxis(ta.l2_project(mt, pt, xt, 2.0).numpy(), 1, -1),
        np.asarray(jlosses.l2_project(
            jnp.asarray(mask), jnp.asarray(pattern), jnp.asarray(x), 2.0)),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        ta.group_lasso(mt, 4).numpy(),
        np.asarray(jlosses.group_lasso(jnp.asarray(mask), 4)), rtol=1e-5)
    np.testing.assert_allclose(
        ta.density_loss(mt, 2).numpy(),
        np.asarray(jlosses.density_loss(jnp.asarray(mask), 2)), rtol=1e-4)

    lvx = np.asarray(jnp.mean(jlosses.local_variance(jnp.asarray(x))[0], -1))
    np.testing.assert_allclose(
        ta.structural_loss(xt, torch.from_numpy(lvx)).numpy(),
        np.asarray(jlosses.structural_loss(jnp.asarray(x), jnp.asarray(lvx))),
        rtol=1e-4,
    )

    logits = _rand(6, 10) * 8
    y = RNG.integers(0, 10, 6)
    targ = RNG.random(6) < 0.5
    np.testing.assert_allclose(
        ta.cw_margin(torch.tensor(logits), torch.tensor(y),
                     torch.tensor(targ), 0.1).numpy(),
        np.asarray(jlosses.cw_margin_switchable(
            jnp.asarray(logits), jnp.asarray(y), 10, jnp.asarray(targ), 0.1)),
        rtol=1e-5,
    )


def test_torch_patch_selection_matches_jax():
    from dorpatch_tpu.attack import patch_selection as jax_ps

    mask = _rand(2, 16, 16, 1)
    got = np.moveaxis(ta.patch_selection(_nchw(mask), 0.15, 4).numpy(), 1, -1)
    want = np.asarray(jax_ps(jnp.asarray(mask), 0.15, 4))
    np.testing.assert_array_equal(got, want)


def _synced_models(img=16, classes=10, seed=3):
    """CifarResNet18 in torch and flax with identical (converted) weights."""
    from dorpatch_tpu.backends.torch_models import CifarResNet18Torch, Normalized
    from dorpatch_tpu.models.convert import convert_cifar_resnet18
    from dorpatch_tpu.models.small import CifarResNet18

    torch.manual_seed(seed)
    tnet = Normalized(CifarResNet18Torch(num_classes=classes)).eval()
    sd = {k: v.numpy() for k, v in tnet.net.state_dict().items()}
    params = convert_cifar_resnet18(sd)
    fnet = CifarResNet18(num_classes=classes)

    def apply(p, x01):
        return fnet.apply(p, (x01 - 0.5) / 0.5)

    return tnet, apply, params


def test_convert_cifar_resnet18_logit_parity():
    tnet, apply, params = _synced_models()
    x = _rand(3, 16, 16, 3)
    want = tnet(_nchw(x)).detach().numpy()
    got = np.asarray(apply(params, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_attack_loss_and_grads_match_jax():
    """The numerical core of the parity claim: identical weights, identical
    EOT masks -> identical loss and gradients in both backends (both stages)."""
    tnet, apply, params = _synced_models()
    cfg = AttackConfig(sampling_size=4, dropout=1, basic_unit=4,
                       structured=1e-3, density=1e-3)
    img = 16
    universe = masks_lib.dropout_universe(img, 1, (0.06, 0.12))
    idx = np.asarray([0, 5, 40, 60])

    x = _rand(2, img, img, 3)
    mask = _rand(2, img, img, 1)
    pattern = _rand(2, img, img, 3)
    y = np.asarray([1, 2])
    lvx = np.asarray(jnp.mean(jlosses.local_variance(jnp.asarray(x))[0], -1))

    attack = DorPatch(apply, params, 10, cfg, remat=False)
    for stage in (0, 1):
        state = attack._init_state(
            jax.random.PRNGKey(0), jnp.asarray(x), jnp.asarray(y), False,
            universe.shape[0])
        grad_fn = jax.value_and_grad(
            attack._loss_and_aux, argnums=(0, 1), has_aux=True)
        (jtotal, _), (jg_mask, jg_pat) = grad_fn(
            jnp.asarray(mask), jnp.asarray(pattern), jnp.asarray(x),
            jnp.asarray(lvx), jnp.asarray(universe[idx]), state, stage)

        tattack = ta.TorchDorPatch(tnet, 10, cfg)
        tstate = ta._State(cfg, 2, universe.shape[0],
                           torch.tensor(y), torch.zeros(2, dtype=torch.bool))
        tm = _nchw(mask).requires_grad_(True)
        tp = _nchw(pattern).requires_grad_(True)
        keep = ta.rects_to_masks(universe[idx], img)
        ttotal, _ = tattack._loss(
            tm, tp, _nchw(x), torch.from_numpy(lvx), keep, tstate, stage)
        ttotal.backward()

        np.testing.assert_allclose(float(jtotal), float(ttotal), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(jg_pat), np.moveaxis(tp.grad.numpy(), 1, -1),
            rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(jg_mask), np.moveaxis(tm.grad.numpy(), 1, -1),
            rtol=1e-3, atol=1e-5)


# ---------------- end-to-end parity (the BASELINE gate) ----------------

def _tiny_cfg(tmp_path, backend, model_dir):
    return ExperimentConfig(
        dataset="cifar10",
        base_arch="resnet18",
        backend=backend,
        batch_size=2,
        num_batches=2,
        synthetic_data=True,
        img_size=32,
        model_dir=model_dir,
        results_root=str(tmp_path / "results"),
        metrics_log=False,
        attack=AttackConfig(
            sampling_size=6, max_iterations=8, sweep_interval=4,
            switch_iteration=4, dropout=1, basic_unit=4, patch_budget=0.15,
        ),
        defense=DefenseConfig(ratios=(0.06, 0.12), chunk_size=18),
    )


@pytest.fixture()
def synced_checkpoint(tmp_path):
    """A seeded CifarResNet18 checkpoint both backends load (the reference's
    checkpoint contract, `/root/reference/utils.py:47-63`)."""
    from dorpatch_tpu.backends.torch_models import CifarResNet18Torch
    from dorpatch_tpu.models.registry import checkpoint_path

    model_dir = str(tmp_path / "pretrained")
    path = checkpoint_path(model_dir, "cifar10", "cifar_resnet18")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    torch.manual_seed(11)
    net = CifarResNet18Torch(num_classes=10)
    torch.save({"state_dict": net.state_dict()}, path)
    return model_dir


@pytest.mark.slow
def test_backend_torch_e2e(tmp_path, synced_checkpoint):
    """`--backend torch` runs the full pipeline and resumes from artifacts."""
    from dorpatch_tpu.pipeline import run_experiment

    cfg = _tiny_cfg(tmp_path, "torch", synced_checkpoint)
    m = run_experiment(cfg, verbose=False)
    assert set(m) >= {"clean_accuracy", "robust_accuracy", "acc_pc",
                      "certified_acc_pc", "certified_asr_pc", "report"}
    assert len(m["certified_asr_pc"]) == 2
    m2 = run_experiment(cfg, verbose=False)
    assert m2["report"] == m["report"]


@pytest.mark.slow
def test_certified_asr_parity_jax_vs_torch(tmp_path, synced_checkpoint):
    """BASELINE.json acceptance gate: with identical weights and the jax
    backend's adversarial patches, the torch oracle's defense evaluation
    reproduces the certified-ASR columns — artifacts interchange on disk and
    the two model/defense stacks agree on every verdict."""
    from dorpatch_tpu.pipeline import run_experiment

    jcfg = _tiny_cfg(tmp_path, "jax-tpu", synced_checkpoint)
    mj = run_experiment(jcfg, verbose=False)

    # drop the cached PatchCleanser verdicts, keep the patches: the torch run
    # must re-derive the verdicts with its own model + defense stack
    from dorpatch_tpu.artifacts import ArtifactStore, results_path

    store = ArtifactStore(results_path(jcfg))
    removed = 0
    for i in range(jcfg.num_batches):
        p = store._pc_path(i)
        if os.path.exists(p):
            os.remove(p)
            removed += 1
    assert removed > 0

    tcfg = _tiny_cfg(tmp_path, "torch", synced_checkpoint)
    mt = run_experiment(tcfg, verbose=False)

    assert mt["certified_asr_pc"] == mj["certified_asr_pc"]
    assert mt["certified_acc_pc"] == mj["certified_acc_pc"]
    assert mt["acc_pc"] == mj["acc_pc"]
    assert mt["clean_accuracy"] == mj["clean_accuracy"]
    assert mt["robust_accuracy"] == mj["robust_accuracy"]
    assert mt["evaluated_images"] == mj["evaluated_images"]


# ---------------- dual occlusion layer ----------------

def test_dual_attack_loss_and_grads_match_jax():
    """`dual=True` parity (`/root/reference/attack.py:208-218`): with the
    same two injected index draws, both backends see the identical union of
    rectangle sets and must agree on loss and gradients (VERDICT r2 ask #8)."""
    tnet, apply, params = _synced_models()
    cfg = AttackConfig(sampling_size=4, dropout=1, basic_unit=4,
                       structured=1e-3, density=1e-3, dual=True)
    img = 16
    universe = masks_lib.dropout_universe(img, 1, (0.06, 0.12))
    idx = np.asarray([0, 5, 40, 60])
    idx2 = np.asarray([3, 17, 22, 51])
    rects = np.concatenate([universe[idx], universe[idx2]], axis=1)  # [S,2K,4]

    x = _rand(2, img, img, 3)
    mask = _rand(2, img, img, 1)
    pattern = _rand(2, img, img, 3)
    y = np.asarray([1, 2])
    lvx = np.asarray(jnp.mean(jlosses.local_variance(jnp.asarray(x))[0], -1))

    attack = DorPatch(apply, params, 10, cfg, remat=False)
    for stage in (0, 1):
        state = attack._init_state(
            jax.random.PRNGKey(0), jnp.asarray(x), jnp.asarray(y), False,
            universe.shape[0])
        grad_fn = jax.value_and_grad(
            attack._loss_and_aux, argnums=(0, 1), has_aux=True)
        (jtotal, _), (jg_mask, jg_pat) = grad_fn(
            jnp.asarray(mask), jnp.asarray(pattern), jnp.asarray(x),
            jnp.asarray(lvx), jnp.asarray(rects), state, stage)

        tattack = ta.TorchDorPatch(tnet, 10, cfg)
        tstate = ta._State(cfg, 2, universe.shape[0],
                           torch.tensor(y), torch.zeros(2, dtype=torch.bool))
        tm = _nchw(mask).requires_grad_(True)
        tp = _nchw(pattern).requires_grad_(True)
        keep = ta.rects_to_masks(rects, img)
        ttotal, _ = tattack._loss(
            tm, tp, _nchw(x), torch.from_numpy(lvx), keep, tstate, stage)
        ttotal.backward()

        np.testing.assert_allclose(float(jtotal), float(ttotal), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(jg_pat), np.moveaxis(tp.grad.numpy(), 1, -1),
            rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(jg_mask), np.moveaxis(tm.grad.numpy(), 1, -1),
            rtol=1e-3, atol=1e-5)


def test_dual_step_draws_second_layer_torch():
    """The torch twin's dual step consumes a second independent draw and
    occludes the union: a pixel kept by draw-1's mask but occluded by
    draw-2's must be filled."""
    tnet, _, _ = _synced_models()
    cfg = AttackConfig(sampling_size=2, dropout=1, dropout_sizes=(0.06,),
                       basic_unit=4, dual=True, max_iterations=1)
    img = 16
    universe = masks_lib.dropout_universe(img, 1, (0.06,))
    tattack = ta.TorchDorPatch(tnet, 10, cfg)
    state = ta._State(cfg, 1, universe.shape[0], torch.tensor([1]),
                      torch.zeros(1, dtype=torch.bool))
    state.best_mask = torch.zeros((1, 1, img, img))
    state.best_pattern = torch.zeros((1, 3, img, img))
    x = _nchw(_rand(1, img, img, 3))
    lvx = torch.ones((1, img, img))
    rng = np.random.default_rng(0)
    m0 = torch.rand((1, 1, img, img))
    p0 = torch.rand((1, 3, img, img))
    out_mask, out_pattern = tattack._step(
        state, m0, p0, x, lvx, universe, 0, rng,
        idx=np.asarray([0, 1]), from_fail=np.zeros(2, bool),
        idx2=np.asarray([2, 3]))
    assert out_pattern.shape == p0.shape and out_mask.shape == m0.shape
    # the step ran on the union: rects of both draws participate
    union = ta.rects_to_masks(
        np.concatenate([universe[[0, 1]], universe[[2, 3]]], axis=1), img)
    single = ta.rects_to_masks(universe[[0, 1]], img)
    assert (union.numpy().sum() < single.numpy().sum())  # strictly more occluded
