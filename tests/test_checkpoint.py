"""Carry checkpointing: orbax roundtrip and mid-stage crash recovery."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.checkpoint import CarryCheckpointer
from dorpatch_tpu.config import AttackConfig


def _tiny_attack(cfg, **kw):
    def apply_fn(params, x):
        s = x.mean(axis=(1, 2))
        return jnp.stack([s[:, 0], s[:, 1], s[:, 2], s.sum(-1) / 3.0], -1) * 10

    return DorPatch(apply_fn, None, 4, cfg, remat=False, **kw)


def _cfg(**kw):
    base = dict(sampling_size=4, max_iterations=6, sweep_interval=3,
                switch_iteration=3, dropout=1, dropout_sizes=(0.06,),
                basic_unit=4, patch_budget=0.15)
    base.update(kw)
    return AttackConfig(**base)


def test_carry_roundtrip(tmp_path):
    atk = _tiny_attack(_cfg())
    x = jax.random.uniform(jax.random.PRNGKey(0), (1, 16, 16, 3))
    state = atk._init_state(jax.random.PRNGKey(1), x, jnp.zeros((1,), jnp.int32),
                            False, 10)
    with CarryCheckpointer(str(tmp_path / "ck")) as ck:
        ck.save(0, 3, state)
        got = ck.restore(state)
        assert got is not None and (got.stage, got.iteration) == (0, 3)
        assert got.stage0_mask is None
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state, got.state)

        # stage-1 snapshot includes the stage-0 artifacts, newest wins
        ck.save(1, 3, state, state.adv_mask, state.adv_pattern)
        got1 = ck.restore(state, (state.adv_mask, state.adv_pattern))
        assert (got1.stage, got1.iteration) == (1, 3)
        np.testing.assert_array_equal(
            np.asarray(got1.stage0_mask), np.asarray(state.adv_mask))


def test_restore_empty_returns_none(tmp_path):
    with CarryCheckpointer(str(tmp_path / "empty")) as ck:
        assert ck.restore(None) is None


def test_clear_removes_snapshots(tmp_path):
    atk = _tiny_attack(_cfg())
    x = jax.random.uniform(jax.random.PRNGKey(0), (1, 16, 16, 3))
    state = atk._init_state(jax.random.PRNGKey(1), x, jnp.zeros((1,), jnp.int32),
                            False, 10)
    with CarryCheckpointer(str(tmp_path / "ck")) as ck:
        ck.save(0, 3, state)
        ck.clear()
        assert ck.restore(state) is None


@pytest.mark.slow
def test_mid_stage_resume_matches_uninterrupted(tmp_path):
    """Kill after the first stage-1 block; the resumed run must finish from
    the snapshot (not restart) and reproduce the uninterrupted result."""
    x = jax.random.uniform(jax.random.PRNGKey(2), (1, 16, 16, 3)) * 0.3
    key = jax.random.PRNGKey(3)

    # uninterrupted oracle
    full = _tiny_attack(_cfg()).generate(x, key=key)

    class Boom(RuntimeError):
        pass

    blocks_seen = []

    def bomb(stage, i, info):
        blocks_seen.append((stage, i))
        if stage == 1 and i == 3:
            raise Boom()

    ck_dir = str(tmp_path / "carry")
    atk = _tiny_attack(_cfg(), checkpointer=CarryCheckpointer(ck_dir))
    atk.on_block_end = bomb
    with pytest.raises(Boom):
        atk.generate(x, key=key)
    atk.checkpointer.close()

    # fresh attack + checkpointer, same inputs: resumes stage 1 from iter 3
    resumed_blocks = []
    atk2 = _tiny_attack(_cfg(), checkpointer=CarryCheckpointer(ck_dir))
    atk2.on_block_end = lambda s, i, info: resumed_blocks.append((s, i))
    res = atk2.generate(x, key=key)
    atk2.checkpointer.close()

    assert resumed_blocks and resumed_blocks[0][0] == 1  # no stage-0 rerun
    assert all(i > 3 or s != 1 for s, i in resumed_blocks) or resumed_blocks[0][1] > 3
    np.testing.assert_allclose(
        np.asarray(res.adv_pattern), np.asarray(full.adv_pattern), atol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(res.adv_mask), np.asarray(full.adv_mask))


def test_fingerprint_mismatch_purged(tmp_path):
    """A snapshot saved under one fingerprint (seed/config identity) must
    never restore into a run with a different fingerprint — it silently
    carries state trained on different images/targets (round-1 advisor
    finding). Mismatches are purged at construction: orbax refuses saves at
    steps below the latest existing one, so a stale high-step snapshot would
    otherwise also block every new save."""
    atk = _tiny_attack(_cfg())
    x = jax.random.uniform(jax.random.PRNGKey(0), (1, 16, 16, 3))
    state = atk._init_state(jax.random.PRNGKey(1), x, jnp.zeros((1,), jnp.int32),
                            False, 10)
    fp1 = {"seed": 1234, "batch": 0}
    with CarryCheckpointer(str(tmp_path / "ck"), fingerprint=fp1) as ck:
        ck.save(0, 3, state)

    # same fingerprint: snapshot survives and restores
    with CarryCheckpointer(str(tmp_path / "ck"), fingerprint=fp1) as ck1:
        got = ck1.restore(state)
        assert got is not None and got.iteration == 3

    # different fingerprint: snapshot purged with a warning, restore is None
    with pytest.warns(UserWarning, match="fingerprint"):
        ck2 = CarryCheckpointer(str(tmp_path / "ck"),
                                fingerprint={"seed": 99, "batch": 0})
    with ck2:
        assert ck2.restore(state) is None

    # legacy snapshots (no fingerprint recorded) are also purged by a
    # fingerprinted open: absence of provenance is not a match
    with CarryCheckpointer(str(tmp_path / "ck2")) as ck4:
        ck4.save(0, 2, state)
    with pytest.warns(UserWarning, match="fingerprint"):
        ck5 = CarryCheckpointer(str(tmp_path / "ck2"), fingerprint=fp1)
    with ck5:
        assert ck5.restore(state) is None


def test_fingerprint_purge_unblocks_new_runs_saves(tmp_path):
    """The regression behind the purge: a stale run's stage-1 snapshot
    (step 10_000_003) would make orbax silently drop this run's stage-0
    saves (monotonic step requirement) AND shadow its restores. After the
    purge, the new run saves and restores its own snapshots normally."""
    atk = _tiny_attack(_cfg())
    x = jax.random.uniform(jax.random.PRNGKey(0), (1, 16, 16, 3))
    state = atk._init_state(jax.random.PRNGKey(1), x, jnp.zeros((1,), jnp.int32),
                            False, 10)
    fp_a, fp_b = {"seed": 1}, {"seed": 2}
    d = str(tmp_path / "ck")
    with CarryCheckpointer(d, fingerprint=fp_a) as ck:
        ck.save(1, 3, state, state.adv_mask, state.adv_pattern)  # step 10_000_003
    with pytest.warns(UserWarning, match="deleting"):
        ck_b = CarryCheckpointer(d, fingerprint=fp_b)
    with ck_b:
        ck_b.save(0, 2, state)                                   # step 2
        assert ck_b._mgr.all_steps() == [2]
    with CarryCheckpointer(d, fingerprint=fp_b) as ck:
        got = ck.restore(state)
        assert got is not None
        assert (got.stage, got.iteration) == (0, 2)


def _two_snapshots(tmp_path):
    atk = _tiny_attack(_cfg())
    x = jax.random.uniform(jax.random.PRNGKey(0), (1, 16, 16, 3))
    state = atk._init_state(jax.random.PRNGKey(1), x,
                            jnp.zeros((1,), jnp.int32), False, 10)
    d = str(tmp_path / "ck")
    with CarryCheckpointer(d) as ck:
        ck.save(0, 2, state)
        ck.save(0, 4, state)
    return d, state


def test_truncated_meta_falls_back_to_previous_snapshot(tmp_path):
    """A crash/ENOSPC mid-save can leave the newest snapshot's meta record
    truncated; restore must warn, delete it, and fall back to the previous
    good snapshot instead of dying mid-resume."""
    d, state = _two_snapshots(tmp_path)
    meta_path = glob.glob(os.path.join(d, "4", "meta*", "*"))[0]
    with open(meta_path, "w") as fh:
        fh.write('{"stage": 0, "iter')  # truncated mid-write
    with CarryCheckpointer(d) as ck:
        with pytest.warns(UserWarning, match="truncated/corrupt"):
            got = ck.restore(state)
        assert got is not None and (got.stage, got.iteration) == (0, 2)
        assert 4 not in ck._mgr.all_steps()  # deleted, not just skipped


def test_corrupt_payload_falls_back_and_unblocks_saves(tmp_path):
    """Readable meta but truncated array payload: the restore attempt fails,
    the snapshot is deleted (a corrupt high step would block every later
    save — orbax requires monotonic steps), and the previous one restores."""
    d, state = _two_snapshots(tmp_path)
    for path in glob.glob(os.path.join(d, "4", "carry", "**", "*"),
                          recursive=True):
        if os.path.isfile(path):
            with open(path, "r+b") as fh:
                fh.truncate(3)
    with CarryCheckpointer(d) as ck:
        with pytest.warns(UserWarning, match="falling back"):
            got = ck.restore(state)
        assert got is not None and (got.stage, got.iteration) == (0, 2)
        # the corrupt step 4 is gone, so a new save at step 3 is accepted
        ck.save(0, 3, state)
        assert sorted(ck._mgr.all_steps()) == [2, 3]
    with CarryCheckpointer(d) as ck:
        got = ck.restore(state)
        assert (got.stage, got.iteration) == (0, 3)


def test_restore_all_snapshots_corrupt_returns_none(tmp_path):
    d, state = _two_snapshots(tmp_path)
    for step in ("2", "4"):
        meta_path = glob.glob(os.path.join(d, step, "meta*", "*"))[0]
        with open(meta_path, "w") as fh:
            fh.write("not json")
    with CarryCheckpointer(d) as ck:
        with pytest.warns(UserWarning):
            assert ck.restore(state) is None


def test_atomic_write_json_and_tolerant_load(tmp_path):
    from dorpatch_tpu.checkpoint import atomic_write_json, load_json

    path = str(tmp_path / "state.json")
    atomic_write_json(path, {"a": 1})
    assert load_json(path) == {"a": 1}
    assert not glob.glob(path + ".tmp.*")  # no stray tmp after commit
    atomic_write_json(path, {"a": 2})
    assert load_json(path) == {"a": 2}
    with open(path, "w") as fh:
        fh.write('{"a": ')  # torn write
    assert load_json(path) is None
    assert load_json(path, default={}) == {}
    assert load_json(str(tmp_path / "missing.json"), default=7) == 7
